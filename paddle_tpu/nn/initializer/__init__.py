"""Parameter initializers — TPU-native rebuild of python/paddle/nn/initializer.

Each initializer is a callable ``(shape, dtype) -> jax array`` drawing from the
framework's seeded counter-based RNG (core/random.py), replacing the reference's
init ops (uniform_random/gaussian_random kernels). Fan computation matches
``nn/initializer/initializer.py:74`` (_compute_fans).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as _random
from ...core.dtype import convert_dtype

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "calculate_gain", "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """paddle.nn.initializer.set_global_initializer"""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _global_initializer(is_bias):
    return _global_bias_init if is_bias else _global_weight_init


def calculate_gain(nonlinearity, param=None):
    """Mirrors paddle.nn.initializer.calculate_gain."""
    recommended = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0), "selu": 3.0 / 4,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity in recommended:
        return recommended[nonlinearity]
    raise ValueError(f"unsupported nonlinearity: {nonlinearity}")


def _compute_fans(shape):
    """Reference: nn/initializer/initializer.py:74."""
    if not shape:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None):
        dtype = convert_dtype(dtype) or jnp.float32
        return self._generate(tuple(int(s) for s in shape), dtype)

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        k = _random.next_key()
        return (self.mean + self.std
                * jax.random.normal(k, shape, jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    """Normal truncated to [mean + a*std, mean + b*std] (default a=-2, b=2)."""

    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _generate(self, shape, dtype):
        k = _random.next_key()
        base = jax.random.truncated_normal(k, self.a, self.b, shape, jnp.float32)
        return (self.mean + self.std * base).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        k = _random.next_key()
        return jax.random.uniform(k, shape, jnp.float32, self.low,
                                  self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        f_in, f_out = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        f_out = self.fan_out if self.fan_out is not None else f_out
        std = self.gain * math.sqrt(2.0 / (f_in + f_out))
        k = _random.next_key()
        return (std * jax.random.normal(k, shape, jnp.float32)).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        f_in, f_out = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        f_out = self.fan_out if self.fan_out is not None else f_out
        limit = self.gain * math.sqrt(6.0 / (f_in + f_out))
        k = _random.next_key()
        return jax.random.uniform(k, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        f_in, _ = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        gain = calculate_gain(self.nonlinearity, self.negative_slope) \
            if self.nonlinearity == "leaky_relu" else calculate_gain(
                self.nonlinearity)
        std = gain / math.sqrt(f_in)
        k = _random.next_key()
        return (std * jax.random.normal(k, shape, jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        f_in, _ = _compute_fans(shape)
        f_in = self.fan_in if self.fan_in is not None else f_in
        gain = calculate_gain(self.nonlinearity, self.negative_slope) \
            if self.nonlinearity == "leaky_relu" else calculate_gain(
                self.nonlinearity)
        limit = gain * math.sqrt(3.0 / f_in)
        k = _random.next_key()
        return jax.random.uniform(k, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def _generate(self, shape, dtype):
        from ...core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        if tuple(arr.shape) != shape:
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _generate(self, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal initializer needs >=2 dims")
        rows, cols = shape[0], int(np.prod(shape[1:]))
        k = _random.next_key()
        a = jax.random.normal(k, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


# paddle also exposes these under their op-style aliases
ConstantInitializer = Constant
NormalInitializer = Normal
UniformInitializer = Uniform
