"""Gradient clipping (reference: python/paddle/nn/clip.py).

ClipGradByGlobalNorm computes one global norm across all grads (single fused
XLA reduction on TPU) and rescales — semantics match the reference including
need_clip filtering.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad Tensor|None) → same structure."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(-max if min is None else min)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max),
                                  stop_gradient=True)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(
                g._data.astype(jnp.float32) ** 2))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data.astype(jnp.float32)
                                   * scale).astype(g._data.dtype),
                                  stop_gradient=True)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Reference: nn/clip.py ClipGradByGlobalNorm."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq.append(jnp.sum(g._data.astype(jnp.float32) ** 2))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data.astype(jnp.float32)
                                   * scale).astype(g._data.dtype),
                                  stop_gradient=True)))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style utility paddle also exposes (paddle.nn.utils)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad = (p._grad.astype(jnp.float32) * scale).astype(
                p._grad.dtype)
    return Tensor(total)
