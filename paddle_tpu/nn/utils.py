"""paddle.nn.utils — weight reparameterizations + parameter/grad helpers.

Reference: python/paddle/nn/utils/ (weight_norm_hook.py, spectral_norm_hook
.py, transform_parameters.py, clip_grad_norm_.py, clip_grad_value_.py).
Reparameterizations install a forward-pre-hook that recomputes the weight
from the reparameterized pieces before every call — same mechanism as the
reference's hook-based design.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except(w, dim):
    """L2 norm over all axes except ``dim`` (keepdims at dim)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(w)))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=False))


def _reshape_g(g, w, dim):
    if dim is None:
        return g
    shape = [1] * w.ndim
    shape[dim] = -1
    return g.reshape(shape)


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def compute(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        # computed through one taped apply so grads reach both g and v
        from ..core.dispatch import apply

        def f(gv, vv):
            n = _norm_except(vv, self.dim)
            if self.dim is None:
                return vv * (gv / n)
            return vv * (_reshape_g(gv, vv, self.dim)
                         / _reshape_g(n, vv, self.dim))
        return apply("weight_norm", f, [g, v])

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute(layer))
        return None


def weight_norm(layer, name="weight", dim=0):
    """Reference: nn.utils.weight_norm — w = g * v / ||v|| with ||·|| over
    every axis except ``dim``; g and v become the trainable parameters."""
    w = getattr(layer, name)
    del layer._parameters[name]
    v = Parameter(w._data)
    v.stop_gradient = False
    g_init = np.asarray(_norm_except(w._data, dim))
    g = Parameter(jnp.asarray(g_init))
    g.stop_gradient = False
    layer.add_parameter(name + "_v", v)
    layer.add_parameter(name + "_g", g)
    hook = _WeightNormHook(name, dim)
    helper = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (hook, helper)
    hook(layer, None)  # materialize once so the attr exists pre-forward
    return layer


def remove_weight_norm(layer, name="weight"):
    hook, helper = layer._weight_norm_hooks.pop(name)
    helper.remove()
    w = hook.compute(layer)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    w2 = Parameter(w._data)
    w2.stop_gradient = False
    layer.add_parameter(name, w2)
    return layer


class _SpectralNormHook:
    def __init__(self, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = n_power_iterations
        self.eps = eps
        self.dim = dim

    def compute(self, layer):
        from ..core.dispatch import apply
        import jax
        w = getattr(layer, self.name + "_orig")
        u = getattr(layer, self.name + "_u")
        mat = w._data
        if self.dim != 0:
            mat = jnp.moveaxis(mat, self.dim, 0)
        mat2 = mat.reshape(mat.shape[0], -1)
        uv = u._data
        for _ in range(self.n):
            v = mat2.T @ uv
            v = v / (jnp.linalg.norm(v) + self.eps)
            uv = mat2 @ v
            uv = uv / (jnp.linalg.norm(uv) + self.eps)
        u._data = jax.lax.stop_gradient(uv)
        vv = jax.lax.stop_gradient(v)

        def f(wa):
            m = wa
            if self.dim != 0:
                m = jnp.moveaxis(m, self.dim, 0)
            m2 = m.reshape(m.shape[0], -1)
            sigma = uv @ (m2 @ vv)
            return wa / sigma

        return apply("spectral_norm", f, [w])

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute(layer))
        return None


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Reference: nn.utils.spectral_norm — divide the weight by its
    largest singular value, estimated by power iteration."""
    w = getattr(layer, name)
    if dim is None:
        dim = 1 if type(layer).__name__ in (
            "Linear", "ColumnParallelLinear", "RowParallelLinear") else 0
    del layer._parameters[name]
    orig = Parameter(w._data)
    orig.stop_gradient = False
    layer.add_parameter(name + "_orig", orig)
    mat = w._data
    if dim != 0:
        mat = jnp.moveaxis(mat, dim, 0)
    h = mat.reshape(mat.shape[0], -1).shape[0]
    rng = np.random.RandomState(0)
    u0 = rng.randn(h).astype(np.asarray(w._data).dtype)
    u0 /= np.linalg.norm(u0) + eps
    layer.register_buffer(name + "_u", Tensor(jnp.asarray(u0)))
    hook = _SpectralNormHook(name, n_power_iterations, eps, dim)
    helper = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_hooks = getattr(layer, "_spectral_norm_hooks", {})
    layer._spectral_norm_hooks[name] = (hook, helper)
    hook(layer, None)
    return layer


def parameters_to_vector(parameters, name=None):
    """Reference: transform_parameters.py — flatten + concat."""
    params = list(parameters)
    from ..core.dispatch import apply
    return apply("parameters_to_vector",
                 lambda *arrs: jnp.concatenate(
                     [a.reshape(-1) for a in arrs]), params)


def vector_to_parameters(vec, parameters, name=None):
    params = list(parameters)
    pos = 0
    arr = vec._data
    for p in params:
        n = int(np.prod(p.shape)) if p.shape else 1
        chunk = arr[pos:pos + n].reshape(p.shape)
        p._data = chunk.astype(p._data.dtype)
        pos += n
    return params


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Reference: clip_grad_norm_.py — scales .grad in place, returns the
    total norm of the gradients."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) \
            ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of gradients is non-finite ({total}); set "
            "error_if_nonfinite=False to scale anyway")
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad = p._grad * coef
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p._grad is not None:
            p._grad = jnp.clip(p._grad, -clip_value, clip_value)
