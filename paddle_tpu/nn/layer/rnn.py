"""Recurrent layers — SimpleRNN/LSTM/GRU cells and multi-layer wrappers.

Reference: python/paddle/nn/layer/rnn.py (SimpleRNNCell:700, LSTMCell:876,
GRUCell:1076, RNN:1290, BiRNN:1366, RNNBase:1457 and the SimpleRNN/LSTM/GRU
user classes). The reference dispatches to a fused cuDNN rnn op on GPU and a
python time loop elsewhere; the TPU-native design compiles the WHOLE
sequence loop as one ``lax.scan`` inside a single dispatched op, so the tape
records one node per (layer, direction) and XLA schedules the recurrence on
device — the cuDNN-fused-RNN equivalent.

Semantics matched: gate orders (LSTM [i,f,g,o]; GRU r,z,c with
``h = z*h_prev + (1-z)*h~``), batch-major default with ``time_major``
option, ``direction='bidirect'`` concatenation, ``sequence_length`` masking
(outputs past a sequence's length are zeros; final states are taken at the
last valid step), ``Uniform(-1/sqrt(hidden), +)`` default init.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor
from .. import initializer as I
from .layers import Layer
from .container import LayerList

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


def _act(name):
    return {"tanh": jnp.tanh, "relu": jax.nn.relu}[name]


# ---------------- functional sequence kernels (one lax.scan per run) ----
def _mask_step(t, seq_lens, new, prev, out):
    """Apply sequence-length masking at step t: states freeze and outputs
    zero once t passes a sequence's length."""
    if seq_lens is None:
        return new, out
    valid = (t < seq_lens)[:, None]
    frozen = tuple(jnp.where(valid, n, p) for n, p in zip(new, prev))
    return frozen, jnp.where(valid, out, jnp.zeros_like(out))


def _scan_rnn(cell_step, x_tm, init_states, seq_lens, reverse):
    """x_tm: [T, B, I] time-major. Returns ([T, B, H], final_states)."""
    T = x_tm.shape[0]
    ts = jnp.arange(T)
    if reverse:
        x_tm = x_tm[::-1]
        ts = ts[::-1]

    def step(carry, xt):
        x_t, t = xt
        new = cell_step(carry, x_t)
        out = new[0]
        new, out = _mask_step(t, seq_lens, new, carry, out)
        return new, out

    final, outs = jax.lax.scan(step, init_states, (x_tm, ts))
    if reverse:
        outs = outs[::-1]
    return outs, final


def _simple_step(w_ih, w_hh, b_ih, b_hh, act):
    def f(carry, x_t):
        (h,) = carry
        g = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        return (act(g),)
    return f


def _lstm_step(w_ih, w_hh, b_ih, b_hh):
    H = w_hh.shape[1]

    def f(carry, x_t):
        h, c = carry
        g = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, fg, cg, o = (g[:, :H], g[:, H:2 * H], g[:, 2 * H:3 * H],
                        g[:, 3 * H:])
        i, fg, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fg), jax.nn.sigmoid(o)
        c_new = fg * c + i * jnp.tanh(cg)
        return (o * jnp.tanh(c_new), c_new)
    return f


def _gru_step(w_ih, w_hh, b_ih, b_hh):
    H = w_hh.shape[1]

    def f(carry, x_t):
        (h,) = carry
        gi = x_t @ w_ih.T + b_ih
        gh = h @ w_hh.T + b_hh
        r = jax.nn.sigmoid(gi[:, :H] + gh[:, :H])
        z = jax.nn.sigmoid(gi[:, H:2 * H] + gh[:, H:2 * H])
        hc = jnp.tanh(gi[:, 2 * H:] + r * gh[:, 2 * H:])
        return (z * h + (1 - z) * hc,)
    return f


_MODES = {
    "simple": (_simple_step, 1, 1),
    "lstm": (_lstm_step, 4, 2),
    "gru": (_gru_step, 3, 1),
}


# ---------------- cells ----------------
class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        n = self.state_components
        zeros = [Tensor(jnp.full((b, self.hidden_size), init_value,
                                 jnp.float32)) for _ in range(n)]
        return zeros[0] if n == 1 else tuple(zeros)


def _uniform_std(hidden_size):
    std = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-std, std)


class _CellCommon(RNNCellBase):
    mode = "simple"

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        _, gates, n_state = _MODES[self.mode]
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.state_components = n_state
        self._activation = activation
        init = _uniform_std(hidden_size)
        self.weight_ih = self.create_parameter(
            (gates * hidden_size, input_size), weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (gates * hidden_size, hidden_size), weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (gates * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            (gates * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=init)

    def _step_fn(self, w_ih, w_hh, b_ih, b_hh):
        mk = _MODES[self.mode][0]
        if self.mode == "simple":
            return mk(w_ih, w_hh, b_ih, b_hh, _act(self._activation))
        return mk(w_ih, w_hh, b_ih, b_hh)

    def _states_tuple(self, states, batch_ref):
        if states is None:
            states = self.get_initial_states(batch_ref)
        if isinstance(states, Tensor):
            states = (states,)
        return tuple(states)

    def forward(self, inputs, states=None):
        states = self._states_tuple(states, inputs)
        ins = [inputs, *states, self.weight_ih, self.weight_hh,
               self.bias_ih, self.bias_hh]
        n_state = self.state_components

        def fwd(x, *arrs):
            st = arrs[:n_state]
            w_ih, w_hh, b_ih, b_hh = arrs[n_state:]
            new = self._step_fn(w_ih, w_hh, b_ih, b_hh)(st, x)
            return tuple(new)

        out = apply(f"{self.mode}_cell", fwd, ins, nout=n_state)
        new = out if isinstance(out, (tuple, list)) else (out,)
        if n_state == 1:
            return new[0], new[0]
        return new[0], tuple(new)


class SimpleRNNCell(_CellCommon):
    """Reference: nn/layer/rnn.py:700."""
    mode = "simple"


class LSTMCell(_CellCommon):
    """Reference: nn/layer/rnn.py:876 (gate order i, f, g, o)."""
    mode = "lstm"

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class GRUCell(_CellCommon):
    """Reference: nn/layer/rnn.py:1076 (h = z*h_prev + (1-z)*h_tilde)."""
    mode = "gru"

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


# ---------------- sequence runners ----------------
def _run_cell_sequence(cell, inputs, states, seq_lens, time_major, reverse):
    """One dispatched op: whole-sequence scan for a single cell. Returns
    (outputs Tensor, tuple-of-final-state Tensors)."""
    if states is None:
        b = inputs.shape[1 if time_major else 0]
        zeros = [Tensor(jnp.zeros((b, cell.hidden_size), jnp.float32))
                 for _ in range(cell.state_components)]
        states = tuple(zeros)
    elif isinstance(states, Tensor):
        states = (states,)
    else:
        states = tuple(states)
    n_state = cell.state_components
    ins = [inputs, *states, cell.weight_ih, cell.weight_hh, cell.bias_ih,
           cell.bias_hh]
    if seq_lens is not None:
        ins.append(seq_lens)

    def fwd(x, *arrs):
        st = tuple(arrs[:n_state])
        w_ih, w_hh, b_ih, b_hh = arrs[n_state:n_state + 4]
        lens = arrs[n_state + 4] if len(arrs) > n_state + 4 else None
        x_tm = x if time_major else jnp.swapaxes(x, 0, 1)
        step = cell._step_fn(w_ih, w_hh, b_ih, b_hh)
        outs, final = _scan_rnn(step, x_tm, st, lens, reverse)
        if not time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return (outs, *final)

    out = apply(f"{cell.mode}_seq", fwd, ins, nout=1 + n_state)
    outs = out[0]
    final = tuple(out[1:])
    return outs, final


class RNN(Layer):
    """Reference: nn/layer/rnn.py:1290 — wraps a cell into a sequence
    runner."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        outs, final = _run_cell_sequence(
            self.cell, inputs, initial_states, sequence_length,
            self.time_major, self.is_reverse)
        if self.cell.state_components == 1:
            return outs, final[0]
        return outs, final


class BiRNN(Layer):
    """Reference: nn/layer/rnn.py:1366."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            st_fw = st_bw = None
        else:
            st_fw, st_bw = initial_states
        out_fw, fin_fw = _run_cell_sequence(
            self.cell_fw, inputs, st_fw, sequence_length, self.time_major,
            False)
        out_bw, fin_bw = _run_cell_sequence(
            self.cell_bw, inputs, st_bw, sequence_length, self.time_major,
            True)
        outs = apply("concat", lambda a, b: jnp.concatenate([a, b], -1),
                     [out_fw, out_bw])
        return outs, (fin_fw, fin_bw)


# ---------------- multi-layer user classes ----------------
class _RNNBase(Layer):
    """Reference: nn/layer/rnn.py:1457 RNNBase."""
    mode = "simple"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(f"unknown direction {direction!r} (use "
                             "'forward' or 'bidirect')")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.state_components = _MODES[self.mode][2]
        cls = {"simple": SimpleRNNCell, "lstm": LSTMCell,
               "gru": GRUCell}[self.mode]

        def mk(in_sz):
            kw = dict(weight_ih_attr=weight_ih_attr,
                      weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
            if self.mode == "simple":
                return cls(in_sz, hidden_size, activation=activation, **kw)
            return cls(in_sz, hidden_size, **kw)

        fw, bw = [], []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 \
                else hidden_size * self.num_directions
            fw.append(mk(in_sz))
            if self.num_directions == 2:
                bw.append(mk(in_sz))
        self._cells_fw = LayerList(fw)
        self._cells_bw = LayerList(bw)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        D, L, n_state = (self.num_directions, self.num_layers,
                         self.state_components)
        batch_idx = 1 if self.time_major else 0
        b = inputs.shape[batch_idx]
        if initial_states is None:
            per_layer = [None] * (L * D)
        else:
            # states: [L*D, B, H] per component (reference layout)
            if n_state == 1:
                comps = (initial_states,) if isinstance(
                    initial_states, Tensor) else tuple(initial_states)
            else:
                comps = tuple(initial_states)
            per_layer = []
            for i in range(L * D):
                per_layer.append(tuple(c[i] for c in comps))

        x = inputs
        finals = []  # (layer, dir) -> tuple of state comps
        for layer in range(L):
            out_fw, fin_fw = _run_cell_sequence(
                self._cells_fw[layer], x, per_layer[layer * D],
                sequence_length, self.time_major, False)
            if D == 2:
                out_bw, fin_bw = _run_cell_sequence(
                    self._cells_bw[layer], x, per_layer[layer * D + 1],
                    sequence_length, self.time_major, True)
                x = apply("concat",
                          lambda a, b: jnp.concatenate([a, b], -1),
                          [out_fw, out_bw])
                finals += [fin_fw, fin_bw]
            else:
                x = out_fw
                finals.append(fin_fw)
            if self.dropout and layer < L - 1 and self.training:
                from .. import functional as F
                x = F.dropout(x, p=self.dropout)

        # stack finals: per component [L*D, B, H]
        stacked = []
        for comp in range(n_state):
            comps = [f[comp] for f in finals]
            stacked.append(apply(
                "stack", lambda *arrs: jnp.stack(arrs), comps))
        if n_state == 1:
            return x, stacked[0]
        return x, tuple(stacked)


class SimpleRNN(_RNNBase):
    """Reference: nn/layer/rnn.py SimpleRNN."""
    mode = "simple"


class LSTM(_RNNBase):
    """Reference: nn/layer/rnn.py LSTM."""
    mode = "lstm"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class GRU(_RNNBase):
    """Reference: nn/layer/rnn.py GRU."""
    mode = "gru"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)
