"""Normalization layers (reference: python/paddle/nn/layer/norm.py).

BatchNorm keeps running stats as non-trainable buffers (_mean/_variance,
matching paddle's state_dict key names) updated with paddle's momentum
convention: running = momentum * running + (1 - momentum) * batch.
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["LayerNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm2D",
           "RMSNorm", "LocalResponseNorm", "SpectralNorm"]


class LayerNorm(Layer):
    """Reference: nn/layer/norm.py LayerNorm."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """RMS normalization over the last dim (incubate fused_rms_norm analog)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        # paddle state_dict keys: _mean / _variance
        self.register_buffer("_mean", Tensor(np.zeros(num_features, "float32")))
        self.register_buffer("_variance",
                             Tensor(np.ones(num_features, "float32")))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/shard_map the batch statistics are global
    automatically (XLA all-reduces the moments); eager single-process behaves
    like BatchNorm (reference: nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            converted = SyncBatchNorm(layer._num_features, layer._momentum,
                                      layer._epsilon,
                                      data_format=layer._data_format)
            converted.weight = layer.weight
            converted.bias = layer.bias
            converted._buffers = layer._buffers
            return converted
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        import jax.numpy as jnp
        from ...core.dispatch import apply

        def fwd(a):
            sq = a * a
            half = self.size // 2
            pads = [(0, 0)] * a.ndim
            pads[1] = (half, self.size - 1 - half)
            padded = jnp.pad(sq, pads)
            acc = sum(padded[:, i:i + a.shape[1]] for i in range(self.size))
            return a / (self.k + self.alpha * acc) ** self.beta
        return apply("local_response_norm", fwd, [x])


class SpectralNorm(Layer):
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        raise NotImplementedError(
            "SpectralNorm is not yet implemented in paddle_tpu")
