"""Long-tail nn layers (reference: python/paddle/nn/layer/{activation,
common,distance,loss,norm,pooling,vision}.py remainder + rnn.py
RNNCellBase / BeamSearchDecoder / dynamic_decode seq2seq API).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .layers import Layer

__all__ = [
    "Bilinear", "Unfold", "Fold", "Maxout", "PairwiseDistance",
    "Softmax2D", "ThresholdedReLU", "RReLU", "PixelShuffle",
    "PixelUnshuffle", "ChannelShuffle", "ZeroPad2D", "Unflatten",
    "InstanceNorm1D", "InstanceNorm3D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool3D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "PoissonNLLLoss", "MultiLabelSoftMarginLoss", "HingeEmbeddingLoss",
    "CosineEmbeddingLoss", "MultiMarginLoss", "TripletMarginLoss",
    "TripletMarginWithDistanceLoss", "SoftMarginLoss", "GaussianNLLLoss",
    "CTCLoss", "RNNTLoss", "HSigmoidLoss", "RNNCellBase",
    "BeamSearchDecoder", "dynamic_decode",
]


class Bilinear(Layer):
    """Reference: nn/layer/common.py Bilinear."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        k, s, p, d = self._args
        return F.unfold(x, k, strides=s, paddings=p, dilations=d)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        o, k, s, p, d = self._args
        return F.fold(x, o, k, strides=s, paddings=p, dilations=d)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._args = (p, epsilon, keepdim)

    def forward(self, x, y):
        p, e, k = self._args
        return F.pairwise_distance(x, y, p=p, epsilon=e, keepdim=k)


class Softmax2D(Layer):
    """Reference: nn/layer/activation.py Softmax2D — softmax over the
    channel dim of NCHW."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self._threshold, self._value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold, self._value)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper,
                       training=self.training)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._args = (upscale_factor, data_format)

    def forward(self, x):
        return F.pixel_shuffle(x, *self._args)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._args = (downscale_factor, data_format)

    def forward(self, x):
        return F.pixel_unshuffle(x, *self._args)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._args = (groups, data_format)

    def forward(self, x):
        return F.channel_shuffle(x, *self._args)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._args = (padding, data_format)

    def forward(self, x):
        return F.zeropad2d(x, *self._args)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self._axis, self._shape = axis, shape

    def forward(self, x):
        from ... import ops
        return ops.unflatten(x, self._axis, self._shape)


def _instance_norm_nd(nd):
    class _InstanceNormND(Layer):
        def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                     weight_attr=None, bias_attr=None, data_format=None,
                     name=None):
            super().__init__()
            self._epsilon = epsilon
            self.weight = None if weight_attr is False else \
                self.create_parameter(
                    [num_features], attr=weight_attr,
                    default_initializer=lambda s, d: jnp.ones(s, d))
            self.bias = None if bias_attr is False else \
                self.create_parameter([num_features], attr=bias_attr,
                                      is_bias=True)

        def forward(self, x):
            return F.instance_norm(x, weight=self.weight, bias=self.bias,
                                   eps=self._epsilon)

    _InstanceNormND.__name__ = f"InstanceNorm{nd}D"
    _InstanceNormND.__doc__ = (
        f"Reference: nn/layer/norm.py InstanceNorm{nd}D.")
    return _InstanceNormND


InstanceNorm1D = _instance_norm_nd(1)
InstanceNorm3D = _instance_norm_nd(3)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._args = (output_size, data_format)

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, *self._args)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size)


class _MaxUnPoolND(Layer):
    _fn = None
    _nd = 0

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self._args
        return getattr(F, f"max_unpool{self._nd}d")(
            x, indices, k, stride=s, padding=p, output_size=o)


class MaxUnPool1D(_MaxUnPoolND):
    _nd = 1


class MaxUnPool2D(_MaxUnPoolND):
    _nd = 2


class MaxUnPool3D(_MaxUnPoolND):
    _nd = 3


def _loss_layer(name, fn, arg_names, n_inputs=2, doc=""):
    class _LossLayer(Layer):
        def __init__(self, **kwargs):
            super().__init__()
            self._kwargs = {k: kwargs.get(k, v) for k, v in arg_names}

        def forward(self, *inputs):
            return fn(*inputs[:n_inputs], **self._kwargs)

    _LossLayer.__name__ = name
    _LossLayer.__doc__ = doc or f"Reference: nn/layer/loss.py {name}."
    return _LossLayer


PoissonNLLLoss = _loss_layer(
    "PoissonNLLLoss", F.poisson_nll_loss,
    [("log_input", True), ("full", False), ("epsilon", 1e-8),
     ("reduction", "mean")])
MultiLabelSoftMarginLoss = _loss_layer(
    "MultiLabelSoftMarginLoss", F.multi_label_soft_margin_loss,
    [("weight", None), ("reduction", "mean")])
HingeEmbeddingLoss = _loss_layer(
    "HingeEmbeddingLoss", F.hinge_embedding_loss,
    [("margin", 1.0), ("reduction", "mean")])
CosineEmbeddingLoss = _loss_layer(
    "CosineEmbeddingLoss", F.cosine_embedding_loss,
    [("margin", 0.0), ("reduction", "mean")], n_inputs=3)
MultiMarginLoss = _loss_layer(
    "MultiMarginLoss", F.multi_margin_loss,
    [("p", 1), ("margin", 1.0), ("weight", None), ("reduction", "mean")])
TripletMarginLoss = _loss_layer(
    "TripletMarginLoss", F.triplet_margin_loss,
    [("margin", 1.0), ("p", 2.0), ("epsilon", 1e-6), ("swap", False),
     ("reduction", "mean")], n_inputs=3)
TripletMarginWithDistanceLoss = _loss_layer(
    "TripletMarginWithDistanceLoss", F.triplet_margin_with_distance_loss,
    [("distance_function", None), ("margin", 1.0), ("swap", False),
     ("reduction", "mean")], n_inputs=3)
SoftMarginLoss = _loss_layer(
    "SoftMarginLoss", F.soft_margin_loss, [("reduction", "mean")])
GaussianNLLLoss = _loss_layer(
    "GaussianNLLLoss", F.gaussian_nll_loss,
    [("full", False), ("epsilon", 1e-6), ("reduction", "mean")],
    n_inputs=3)


class CTCLoss(Layer):
    """Reference: nn/layer/loss.py CTCLoss."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self._blank, reduction=self._reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    """Reference: nn/layer/loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (blank, fastemit_lambda, reduction)

    def forward(self, input, label, input_lengths, label_lengths):
        b, f, r = self._args
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=b, fastemit_lambda=f, reduction=r)


class HSigmoidLoss(Layer):
    """Reference: nn/layer/loss.py HSigmoidLoss (default complete binary
    tree)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "custom-tree HSigmoidLoss is not implemented")
        self._num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_classes - 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes,
                               self.weight, self.bias,
                               path_table=path_table, path_code=path_code)


# ---------------- seq2seq decoding ----------------

class RNNCellBase(Layer):
    """Reference: nn/layer/rnn.py RNNCellBase — the cell contract
    (state shapes/init) used by RNN and the decoders."""

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        hidden = self.hidden_size
        import jax.numpy as _jnp
        mk = lambda: Tensor(_jnp.full((batch, hidden), init_value,
                                      _jnp.float32))
        if getattr(self, "state_shape", None) is not None and \
                isinstance(self.state_shape, (list, tuple)) and \
                len(self.state_shape) == 2:
            return (mk(), mk())
        return mk()


class BeamSearchDecoder:
    """Reference: nn/layer/rnn.py BeamSearchDecoder — beam search over an
    RNN cell with an output projection; used through dynamic_decode.
    Host-driven (python loop in dynamic_decode), math on device."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        return initial_cell_states

    def _logits(self, out):
        return self.output_fn(out) if self.output_fn is not None else out


def dynamic_decode(decoder, inits=None, max_step_num=100, **kwargs):
    """Reference: nn/layer/rnn.py dynamic_decode — run a BeamSearchDecoder
    to completion; returns (ids [B, beam, T], scores [B, beam])."""
    import jax

    cell = decoder.cell
    K = decoder.beam_size
    state = inits
    # batch from the first state leaf
    leaf = state[0] if isinstance(state, (list, tuple)) else state
    B = leaf.shape[0]

    def emb(tok):
        if decoder.embedding_fn is not None:
            return decoder.embedding_fn(tok)
        return tok

    neg_inf = -1e9
    # run the first step from start tokens to seed K beams per batch
    tok0 = Tensor(jnp.full((B,), decoder.start_token, jnp.int64))
    out, state = cell(emb(tok0), state)
    logp0 = jnp.asarray(
        jax.nn.log_softmax(decoder._logits(out)._data, axis=-1))
    V = logp0.shape[-1]
    scores, ids = jax.lax.top_k(logp0, K)             # [B, K]
    beam_ids = [np.asarray(ids)]
    beam_scores = jnp.asarray(scores)                 # [B, K]
    finished = np.asarray(ids) == decoder.end_token

    def tile_state(s):
        # [B, H] -> [B*K, H]
        return Tensor(jnp.repeat(s._data, K, axis=0))

    state = tuple(tile_state(s) for s in state) \
        if isinstance(state, (list, tuple)) else tile_state(state)

    cur_tokens = Tensor(jnp.asarray(np.asarray(ids).reshape(-1)))
    for _ in range(max_step_num - 1):
        if finished.all():
            break
        out, new_state = cell(emb(cur_tokens), state)
        logp = jnp.asarray(jax.nn.log_softmax(
            decoder._logits(out)._data, axis=-1)).reshape(B, K, V)
        # finished beams only extend with end_token at no cost
        fin = jnp.asarray(finished)[:, :, None]
        step_scores = jnp.where(fin, neg_inf, logp)
        step_scores = step_scores.at[:, :, decoder.end_token].set(
            jnp.where(fin[:, :, 0], 0.0,
                      step_scores[:, :, decoder.end_token]))
        total = beam_scores[:, :, None] + step_scores       # [B, K, V]
        flat = total.reshape(B, K * V)
        beam_scores, flat_idx = jax.lax.top_k(flat, K)      # [B, K]
        parent = np.asarray(flat_idx // V)                  # [B, K]
        tok = np.asarray(flat_idx % V)                      # [B, K]
        # reorder history + states by parent beam
        beam_ids = [h[np.arange(B)[:, None], parent] for h in beam_ids]
        beam_ids.append(tok)
        gather = (np.arange(B)[:, None] * K + parent).reshape(-1)

        def regather(s_new):
            return Tensor(jnp.take(s_new._data, jnp.asarray(gather),
                                   axis=0))

        state = tuple(regather(s) for s in new_state) \
            if isinstance(new_state, (list, tuple)) else regather(new_state)
        finished = finished[np.arange(B)[:, None], parent] | \
            (tok == decoder.end_token)
        cur_tokens = Tensor(jnp.asarray(tok.reshape(-1)))

    ids_arr = np.stack(beam_ids, axis=-1)                   # [B, K, T]
    return (Tensor(jnp.asarray(ids_arr)),
            Tensor(jnp.asarray(beam_scores)))
