"""Convolution layers (reference: python/paddle/nn/layer/conv.py).

Default weight initializer matches the reference (nn/layer/conv.py:149):
Normal(0, sqrt(2 / (prod(kernel) * in_channels))).
"""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose"]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (list(v) * n if len(v) == 1 else v))
    return (int(v),) * n


class _ConvNd(Layer):
    def __init__(self, nd, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, padding_mode, weight_attr,
                 bias_attr, data_format, transposed=False, output_padding=0):
        super().__init__()
        assert in_channels % groups == 0, \
            "in_channels must be divisible by groups"
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nd)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._transposed = transposed
        self._output_padding = output_padding
        if transposed:
            filter_shape = [in_channels, out_channels // groups,
                            *self._kernel_size]
            default_init = None
        else:
            filter_shape = [out_channels, in_channels // groups,
                            *self._kernel_size]
            fan = int(np.prod(self._kernel_size)) * in_channels
            default_init = I.Normal(0.0, (2.0 / fan) ** 0.5)
        self.weight = self.create_parameter(
            shape=filter_shape, attr=weight_attr,
            default_initializer=default_init)
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}, "
                f"padding={self._padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    """Reference: nn/layer/conv.py Conv2D (NCHW, weight [O, I/g, kh, kw])."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  self._data_format, output_size)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  self._data_format, output_size)
