"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "LogSoftmax",
           "LeakyReLU", "ELU", "SELU", "CELU", "Hardtanh", "Hardsigmoid",
           "Hardswish", "Hardshrink", "Softshrink", "Tanhshrink", "Softplus",
           "Softsign", "Mish", "LogSigmoid", "Silu", "Swish", "PReLU", "GLU"]


def _simple(name, fn_name, **fixed):
    def forward(self, x):
        return getattr(F, fn_name)(x, **fixed, **self._kw)

    def __init__(self, *args, name=None, **kw):  # noqa: N807
        Layer.__init__(self)
        # positional args map onto the functional's keyword order
        self._kw = kw
        if args:
            import inspect
            sig = list(inspect.signature(getattr(F, fn_name)).parameters)[1:]
            for a, k in zip(args, sig):
                self._kw[k] = a

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
GELU = _simple("GELU", "gelu")
Sigmoid = _simple("Sigmoid", "sigmoid")
Tanh = _simple("Tanh", "tanh")
Softmax = _simple("Softmax", "softmax")
LogSoftmax = _simple("LogSoftmax", "log_softmax")
LeakyReLU = _simple("LeakyReLU", "leaky_relu")
ELU = _simple("ELU", "elu")
SELU = _simple("SELU", "selu")
CELU = _simple("CELU", "celu")
Hardtanh = _simple("Hardtanh", "hardtanh")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardshrink = _simple("Hardshrink", "hardshrink")
Softshrink = _simple("Softshrink", "softshrink")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Softplus = _simple("Softplus", "softplus")
Softsign = _simple("Softsign", "softsign")
Mish = _simple("Mish", "mish")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
GLU = _simple("GLU", "glu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
