"""Common layers: Linear, Dropout, Embedding, Flatten, Pad, Upsample, Identity.

Reference: python/paddle/nn/layer/common.py.
"""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .layers import Layer

__all__ = ["Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
           "Embedding", "Flatten", "Identity", "Pad1D", "Pad2D", "Pad3D",
           "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
           "CosineSimilarity"]


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = x @ W + b, W: [in_features, out_features]
    (reference: nn/layer/common.py Linear — paddle keeps W untransposed)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self._in_features}, "
                f"out_features={self._out_features}")


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Embedding(Layer):
    """Reference: nn/layer/common.py Embedding; weight [num_embeddings, dim]."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx if padding_idx is None or \
            padding_idx >= 0 else num_embeddings + padding_idx
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr)
        if self._padding_idx is not None:
            with_pad = np.array(self.weight.numpy())
            with_pad[self._padding_idx] = 0
            self.weight.set_value(with_pad)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, mode=self._mode, value=self._value,
                     data_format=self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format, name)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", align_corners=True,
                         data_format=data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)
