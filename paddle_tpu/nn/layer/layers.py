"""Layer — base class for all NN modules.

Reference: python/paddle/nn/layer/layers.py:333 (class Layer): parameter/buffer/
sublayer registries, hooks, state_dict round-trip, train/eval flags. TPU-native
notes: parameters are eager Tensors over jax.Array; `to(dtype=...)` casts in
place; everything is functionalization-friendly so jit.to_static can lift
parameters/buffers into traced inputs.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Iterator, Optional, Tuple

import numpy as np

from ...core.dtype import convert_dtype, get_default_dtype, is_floating
from ...core.tensor import Parameter, Tensor
from ...framework.param_attr import ParamAttr
from .. import initializer as I

__all__ = ["Layer"]


class HookRemoveHelper:
    next_hook_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        HookRemoveHelper.next_hook_id[0] += 1
        self._hook_id = HookRemoveHelper.next_hook_id[0]

    def remove(self):
        self._hooks.pop(self._hook_id, None)


# monotonic counter bumped on EVERY parameter/sublayer/buffer registration
# or removal, process-wide. The staged train step's fast dispatch memo
# (jit.api.StaticFunction) snapshots it at state-walk time and re-walks
# when it moved — a structural edit to a captured module (progressive
# unfreezing, growing a Sequential mid-fit) retraces instead of silently
# replaying the old program without the new parameters.
STRUCT_VERSION = [0]


class Layer:
    """Base network module (reference Layer, nn/layer/layers.py:333)."""

    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks: "OrderedDict[int, callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, callable]" = OrderedDict()
        self._name = name_scope or self.__class__.__name__.lower()

    # ---- forward plumbing ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()")

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._hook_id] = hook
        return helper

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._hook_id] = hook
        return helper

    # ---- train/eval ----
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # ---- parameter creation ----
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        """Reference: Layer.create_parameter → LayerHelperBase.create_parameter.
        Default initializers: XavierUniform for weights, Constant(0) for bias
        (base/layer_helper_base.py), overridable per-layer or globally."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        init = attr.initializer or I._global_initializer(is_bias) \
            or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        data = init(shape, dtype) if callable(init) else init
        p = Parameter(data, dtype=dtype, trainable=attr.trainable,
                      name=attr.name)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        t = Tensor(np.zeros([0], dtype="float32"),
                   dtype=convert_dtype(dtype) or self._dtype, name=name)
        t.persistable = persistable
        return t

    # ---- registration ----
    def add_parameter(self, name, parameter) -> Optional[Parameter]:
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError(
                f"add_parameter expects a Parameter, got {type(parameter)}")
        STRUCT_VERSION[0] += 1
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer) -> "Layer":
        if not isinstance(sublayer, Layer):
            raise TypeError(
                f"add_sublayer expects a Layer, got {type(sublayer)}")
        STRUCT_VERSION[0] += 1
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            raise TypeError(
                f"register_buffer expects a Tensor, got {type(tensor)}")
        STRUCT_VERSION[0] += 1
        self._buffers[name] = tensor
        if persistable:
            self._non_persistable_buffer_names_set.discard(name)
        else:
            self._non_persistable_buffer_names_set.add(name)

    # ---- attribute magic ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "super().__init__() must be called before assigning "
                    "parameters")
            for registry in (layers, buffers):
                if registry is not None:
                    registry.pop(name, None)
            STRUCT_VERSION[0] += 1
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "super().__init__() must be called before assigning "
                    "sublayers")
            for registry in (params, buffers):
                if registry is not None:
                    registry.pop(name, None)
            STRUCT_VERSION[0] += 1
            layers[name] = value
        elif buffers is not None and name in buffers:
            # assigning a Tensor over a registered buffer keeps buffer-ness
            buffers[name] = value
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for registry in (self._parameters, self._sub_layers, self._buffers):
            if name in registry:
                STRUCT_VERSION[0] += 1
                del registry[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) \
            + list(self._sub_layers) + list(self._buffers)

    # ---- traversal ----
    def children(self) -> Iterator["Layer"]:
        for _, layer in self.named_children():
            yield layer

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self=False):
        return [layer for _, layer in self.named_sublayers(
            include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            if layer is None or id(layer) in layers_set:
                continue
            layers_set.add(id(layer))
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(prefix=sub_prefix,
                                             include_self=False,
                                             layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for layer_prefix, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for layer_prefix, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name), b

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        """name → Tensor for all parameters + persistable buffers
        (reference: Layer.state_dict)."""
        dest = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            dest[name] = p
        prefix = structured_name_prefix
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for layer_prefix, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or name in layer._non_persistable_buffer_names_set:
                    continue
                dest[f"{layer_prefix}.{name}" if layer_prefix else name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load values into matching params/buffers; returns
        (missing_keys, unexpected_keys) like the reference."""
        own = self.state_dict()
        missing, matched = [], set()
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            src = state_dict[name]
            src_arr = src.numpy() if isinstance(src, Tensor) else \
                np.asarray(src)
            if list(src_arr.shape) != list(target.shape):
                raise ValueError(
                    f"state_dict['{name}'] has shape {list(src_arr.shape)} "
                    f"but expects {list(target.shape)}")
            target.set_value(src_arr)
            matched.add(name)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # ---- misc ----
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = convert_dtype(dtype)
            for p in self.parameters():
                if is_floating(p.dtype):
                    p._data = p._data.astype(dtype)
            for b in self.buffers():
                if b is not None and is_floating(b.dtype):
                    b._data = b._data.astype(dtype)
            for layer in self.sublayers(include_self=True):
                layer._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self.named_children():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = f"{type(self).__name__}({extra}"
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
