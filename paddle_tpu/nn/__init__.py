"""paddle_tpu.nn — neural network layers and functional ops.

Reference namespace: python/paddle/nn/__init__.py (Layer base
nn/layer/layers.py:333, functional ops nn/functional/, initializers
nn/initializer/, grad clip nn/clip.py).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue, clip_grad_norm_,
)
from .layer import *  # noqa: F401,F403
from .layer import Layer  # noqa: F401
