"""Optimizer base class (reference: python/paddle/optimizer/optimizer.py).

Semantics kept from the reference: explicit parameter lists (dygraph mode),
param groups as dicts, grad clip hook, L2 regularization fold-in, accumulator
state_dict round-trip, master weights (multi_precision) for low-precision
params. TPU-native: updates are raw jnp expressions on the underlying
jax.Array — under jit.to_static the whole step (fwd+bwd+update) stages into
one XLA program; eagerly XLA fuses each param update chain.
"""
from __future__ import annotations

from collections import OrderedDict, defaultdict

import jax.numpy as jnp

from ..core.dtype import is_floating
from ..core.tensor import Parameter, Tensor
from ..regularizer import L1Decay, L2Decay
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if parameters is None:
            from ..static import program as _sp
            if _sp.in_static_mode():
                parameters = []  # filled by minimize from the Program
            else:
                raise ValueError(
                    "parameters is required in dygraph mode: pass "
                    "model.parameters() (reference: optimizer.py dygraph "
                    "check)")
        parameters = list(parameters)
        if parameters and isinstance(parameters[0], dict):
            self._param_groups = []
            self._parameter_list = []
            for g in parameters:
                group = dict(g)
                group["params"] = list(g["params"])
                self._param_groups.append(group)
                self._parameter_list += group["params"]
        else:
            self._parameter_list = parameters
            self._param_groups = [{"params": parameters}]
        self._learning_rate = learning_rate
        self.regularization = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict = defaultdict(dict)  # acc name -> {pid: arr}
        self._master_weights: dict = {}  # pid -> f32 arr
        self._pid_to_param = {id(p): p for p in self._parameter_list}
        self._global_step = 0
        self._lr_override = None  # set by jit whole-step staging (traced lr)
        # distributed hooks (set by DygraphShardingOptimizer): reshard the
        # grad before the sharded accumulator update (ZeRO reduce-scatter)
        # and the updated param after it (all-gather / keep-sharded)
        self._dist_grad_hook = None
        self._dist_out_hook = None

    # ---- learning rate ----
    def get_lr(self):
        if self._lr_override is not None:
            return self._lr_override
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is an LRScheduler; "
                "call scheduler.step() instead")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---- weight decay ----
    def _coupled_decay_coeff(self, group):
        """L2 coeff folded into grads (SGD/Momentum/Adam reference behavior).
        AdamW overrides to return 0 here and applies decoupled decay."""
        wd = group.get("weight_decay", self.regularization)
        if wd is None:
            return 0.0, None
        if isinstance(wd, L2Decay):
            return wd.coeff, None
        if isinstance(wd, L1Decay):
            return 0.0, wd.coeff
        return float(wd), None

    # ---- accumulators ----
    def _get_accumulator(self, name, p, init=None):
        d = self._accumulators[name]
        pid = id(p)
        if pid not in d:
            dtype = jnp.float32 if self._use_master(p) else p._data.dtype
            d[pid] = jnp.zeros(p._data.shape, dtype) if init is None else init
        return d[pid]

    def _set_accumulator(self, name, p, value):
        self._accumulators[name][id(p)] = value

    def _use_master(self, p):
        return self._multi_precision and p._data.dtype in (
            jnp.bfloat16, jnp.float16)

    def _master_of(self, p):
        pid = id(p)
        if pid not in self._master_weights:
            self._master_weights[pid] = p._data.astype(jnp.float32)
        return self._master_weights[pid]

    # ---- the step ----
    def step(self):
        for group in self._param_groups:
            params_grads = []
            for p in group["params"]:
                if p.stop_gradient or p._grad is None:
                    continue
                params_grads.append((p, Tensor(p._grad, stop_gradient=True)))
            if not params_grads:
                continue
            if self._grad_clip is not None:
                params_grads = self._grad_clip(params_grads)
            lr = group.get("learning_rate", 1.0)
            lr = self.get_lr() * lr if isinstance(lr, (int, float)) else lr
            l2, l1 = self._coupled_decay_coeff(group)
            for p, g in params_grads:
                garr = g._data
                use_master = self._use_master(p)
                w = self._master_of(p) if use_master else p._data
                garr = garr.astype(w.dtype)
                if l2:
                    garr = garr + l2 * w
                if l1:
                    garr = garr + l1 * jnp.sign(w)
                plr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                    if isinstance(p, Parameter) and p.optimize_attr else lr
                if self._dist_grad_hook is not None:
                    garr = self._dist_grad_hook(p, garr)
                new_w = self._update(p, w, garr, plr, group)
                if self._dist_out_hook is not None:
                    new_w = self._dist_out_hook(p, new_w)
                if use_master:
                    self._master_weights[id(p)] = new_w
                    p._data = new_w.astype(p._data.dtype)
                else:
                    p._data = new_w
        self._global_step += 1

    def _update(self, p, w, g, lr, group):
        """Return the new param value (raw array). Subclasses implement."""
        raise NotImplementedError

    # ---- grads ----
    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def backward(self, loss, retain_graph=False):
        loss.backward(retain_graph=retain_graph)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import program as _sp
        if _sp.in_static_mode() and isinstance(loss, _sp.Variable):
            # static graph: record; Executor.run replays backward+step
            loss._program.minimize_ops.append((self, loss))
            return None, None
        self.backward(loss)
        self.step()
        return None, None

    # ---- state dict ----
    def state_dict(self):
        """acc-name_param-name → Tensor + LR_Scheduler + master weights
        (reference format: optimizer.py state_dict)."""
        state = OrderedDict()
        for acc_name, per_param in self._accumulators.items():
            for pid, arr in per_param.items():
                p = self._pid_to_param.get(pid)
                if p is None:
                    continue
                state[f"{p.name}_{acc_name}"] = Tensor(arr,
                                                       stop_gradient=True)
        if self._master_weights:
            mw = {}
            for pid, arr in self._master_weights.items():
                p = self._pid_to_param.get(pid)
                if p is not None:
                    mw[p.name] = Tensor(arr, stop_gradient=True)
            state["master_weights"] = mw
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        state["global_step"] = self._global_step
        return state

    def set_state_dict(self, state_dict):
        by_name = {p.name: p for p in self._parameter_list}
        for key, value in state_dict.items():
            if key == "LR_Scheduler":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(value)
                continue
            if key == "global_step":
                self._global_step = int(value)
                continue
            if key == "master_weights":
                for pname, t in value.items():
                    p = by_name.get(pname)
                    if p is not None:
                        self._master_weights[id(p)] = jnp.asarray(
                            t.numpy(), jnp.float32)
                continue
            # key = f"{param_name}_{acc_name}"; param names contain no '_'
            # ambiguity risk, so match by longest param-name prefix
            matched = None
            for pname, p in by_name.items():
                if key.startswith(pname + "_"):
                    if matched is None or len(pname) > len(matched[0]):
                        matched = (pname, p)
            if matched is None:
                continue
            pname, p = matched
            acc_name = key[len(pname) + 1:]
            arr = value._data if isinstance(value, Tensor) else \
                jnp.asarray(value)
            self._accumulators[acc_name][id(p)] = arr

    # ---- state materialization (skip the eager warmup in jit staging) ----
    def materialize(self):
        """Create all accumulators (and master weights) up front so the
        compiled whole-step program can stage them as inputs without an
        eager first step."""
        for group in self._param_groups:
            for p in group["params"]:
                if p.stop_gradient:
                    continue
                if self._use_master(p):
                    self._master_of(p)
                self._materialize_param(p)

    def _materialize_param(self, p):
        """Subclasses pre-create their accumulators for param p."""

    # ---- functionalization hooks for jit.to_static ----
    def _state_slots(self):
        """[(container_dict, key)] of every mutable raw array — the compile
        layer swaps these with tracers to stage optimizer state."""
        slots = []
        for per_param in self._accumulators.values():
            for pid in per_param:
                slots.append((per_param, pid))
        for pid in self._master_weights:
            slots.append((self._master_weights, pid))
        return slots
