"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,
adamw,adagrad,rmsprop,adadelta,adamax,lamb,rprop,lbfgs}.py).

Each `_update` is a pure jnp expression; XLA fuses it into a single kernel per
parameter (the reference needs hand-fused CUDA kernels for this —
phi/kernels/gpu/fused_adam_kernel.cu).
"""
from __future__ import annotations

import jax.numpy as jnp

import numpy as np

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "RMSProp",
           "Adadelta", "Adamax", "Lamb", "Rprop", "LBFGS"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _update(self, p, w, g, lr, group):
        return w - lr * g


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update(self, p, w, g, lr, group):
        v = self._get_accumulator("velocity", p)
        v = self._momentum * v + g
        self._set_accumulator("velocity", p, v)
        if self._use_nesterov:
            return w - lr * (g + self._momentum * v)
        return w - lr * v


class Adam(Optimizer):
    """Reference: python/paddle/optimizer/adam.py."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    # Pallas fused path (reference capability: fused_adam_kernel.cu multi-
    # tensor Adam): one VMEM pass reads (w, g, m, v) and writes (w', m', v').
    # Auto-enabled on TPU for large dense params; _fused_wd is folded in by
    # AdamW (decoupled decay inside the same kernel pass).
    use_fused = None  # None = auto (TPU + size threshold)
    _FUSED_MIN_SIZE = 16384

    def _fused_ok(self, w, g):
        import jax as _jax
        if self.use_fused is False:
            return False
        if w.ndim == 0 or w.size < self._FUSED_MIN_SIZE or \
                w.shape != g.shape:
            return False
        if not (jnp.issubdtype(w.dtype, jnp.floating)
                and jnp.issubdtype(g.dtype, jnp.floating)):
            return False
        if getattr(self, "_dist_update_info", None) is not None:
            # ZeRO: DygraphShardingOptimizer published the per-param merged
            # spec, so the kernel shard_maps over the local shard (VERDICT
            # r3 weak #6: fused must not be disabled exactly where it
            # matters most)
            if self.use_fused:
                return True
            return _jax.default_backend() == "tpu" and \
                self._gate_allows(w)
        if self._dist_grad_hook is not None:
            # sharded state with no published spec: the GSPMD jnp path
            # partitions cleanly; a bare pallas_call would force a gather
            return False
        if _jax.device_count() > 1:
            # multi-chip: params may be GSPMD/TP-sharded (unknowable at
            # trace time) and a bare pallas_call cannot be partitioned —
            # the jnp path partitions cleanly
            return False
        if self.use_fused:
            return True
        # auto: TPU + the demotion gate — BENCH_r05 measured the Pallas
        # fused update LOSING to the XLA-fused jnp chain on the real chip,
        # so the kernel serves only where an A/B verdict says it wins
        # (nearest same-dtype/rank verdict within 4x: params sweep shapes)
        return _jax.default_backend() == "tpu" and self._gate_allows(w)

    @staticmethod
    def _gate_allows(w):
        from ..ops.pallas import _common as _gate
        return _gate.pallas_default("fused_adamw", _gate.shape_sig(w),
                                    allow_nearest=True)

    def _update(self, p, w, g, lr, group, fused_wd=0.0):
        m = self._get_accumulator("moment1", p)
        v = self._get_accumulator("moment2", p)
        t = self._get_accumulator("beta_pow", p,
                                  init=jnp.zeros((), jnp.float32))
        t = t + 1
        self._set_accumulator("beta_pow", p, t)
        if self._fused_ok(w, g) and m.shape == w.shape:
            from ..ops.pallas.fused_adamw import fused_adamw
            bc1 = 1.0 / (1 - self._beta1 ** t)
            bc2 = 1.0 / (1 - self._beta2 ** t)
            info = getattr(self, "_dist_update_info", None)
            if info is not None:
                # ZeRO/TP-sharded state: run the kernel per-shard under
                # shard_map — each device updates its 1/N slice in VMEM,
                # no gather (reference: fused_adam + sharding stage
                # composition, dygraph_sharding_optimizer.py:470)
                import jax as _jax
                from jax import shard_map
                from jax.sharding import PartitionSpec as _P
                mesh, merged = info
                spec = merged(p, w.shape, True)
                b1, b2, eps, wd = (self._beta1, self._beta2,
                                   self._epsilon, fused_wd)

                def local(wl, gl, ml, vl, lr_, c1, c2):
                    return fused_adamw(wl, gl, ml, vl, lr_, b1, b2, eps,
                                       wd, c1, c2)

                scalar = _P()
                w2, m2, v2 = shard_map(
                    local, mesh=mesh,
                    in_specs=(spec, spec, spec, spec, scalar, scalar,
                              scalar),
                    out_specs=(spec, spec, spec), check_vma=False)(
                        w, g.astype(jnp.float32), m, v,
                        jnp.asarray(lr, jnp.float32),
                        jnp.asarray(bc1, jnp.float32),
                        jnp.asarray(bc2, jnp.float32))
            else:
                w2, m2, v2 = fused_adamw(w, g, m, v, lr, self._beta1,
                                         self._beta2, self._epsilon,
                                         fused_wd, bc1, bc2)
            # keep the accumulators' dtype (the kernel computes f32)
            self._set_accumulator("moment1", p, m2.astype(m.dtype))
            self._set_accumulator("moment2", p, v2.astype(v.dtype))
            return w2
        if fused_wd:
            w = w * (1.0 - lr * fused_wd)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        return w - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py):
    w ← w - lr * coeff * w applied outside the adaptive update."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         name=name)
        self._decay_coeff = float(weight_decay) if not hasattr(
            weight_decay, "coeff") else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _coupled_decay_coeff(self, group):
        return 0.0, None  # decay is decoupled

    def _update(self, p, w, g, lr, group):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        wd = group.get("weight_decay", self._decay_coeff)
        wd = wd.coeff if hasattr(wd, "coeff") else float(wd)
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            wd = 0.0
        # decay folds into the fused kernel pass (fused_adamw wd operand);
        # the jnp fallback applies it identically
        return super()._update(p, w, g, lr, group, fused_wd=wd)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value
                 =0.0, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _update(self, p, w, g, lr, group):
        acc = self._get_accumulator(
            "moment", p, init=jnp.full(p._data.shape, self._initial,
                                       jnp.float32 if self._use_master(p)
                                       else p._data.dtype))
        acc = acc + g * g
        self._set_accumulator("moment", p, acc)
        return w - lr * g / (jnp.sqrt(acc) + self._epsilon)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update(self, p, w, g, lr, group):
        ms = self._get_accumulator("mean_square", p)
        ms = self._rho * ms + (1 - self._rho) * g * g
        self._set_accumulator("mean_square", p, ms)
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_accumulator("mean_grad", p, mg)
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._get_accumulator("momentum", p)
        mom = self._momentum * mom + lr * g / denom
        self._set_accumulator("momentum", p, mom)
        return w - mom


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._rho = rho

    def _update(self, p, w, g, lr, group):
        avg_sq = self._get_accumulator("avg_squared_grad", p)
        avg_up = self._get_accumulator("avg_squared_update", p)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * g * g
        update = jnp.sqrt(avg_up + self._epsilon) / \
            jnp.sqrt(avg_sq + self._epsilon) * g
        avg_up = self._rho * avg_up + (1 - self._rho) * update * update
        self._set_accumulator("avg_squared_grad", p, avg_sq)
        self._set_accumulator("avg_squared_update", p, avg_up)
        return w - lr * update


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update(self, p, w, g, lr, group):
        m = self._get_accumulator("moment", p)
        u = self._get_accumulator("inf_norm", p)
        t = self._get_accumulator("beta1_pow", p,
                                  init=jnp.ones((), jnp.float32))
        t = t * self._beta1
        m = self._beta1 * m + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * u, jnp.abs(g))
        self._set_accumulator("moment", p, m)
        self._set_accumulator("inf_norm", p, u)
        self._set_accumulator("beta1_pow", p, t)
        return w - lr / (1 - t) * m / (u + self._epsilon)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, p, w, g, lr, group):
        m = self._get_accumulator("moment1", p)
        v = self._get_accumulator("moment2", p)
        t = self._get_accumulator("beta_pow", p,
                                  init=jnp.zeros((), jnp.float32))
        t = t + 1
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * g * g
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)
        self._set_accumulator("beta_pow", p, t)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) \
            else self._wd
        update = r + wd * w
        w_norm = jnp.linalg.norm(w)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where(w_norm > 0, jnp.where(u_norm > 0, w_norm / u_norm,
                                                1.0), 1.0)
        return w - lr * trust * update


# ---- accumulator pre-materialization (used by jit whole-step staging) ----
def _mat_momentum(self, p):
    self._get_accumulator("velocity", p)


def _mat_adam(self, p):
    self._get_accumulator("moment1", p)
    self._get_accumulator("moment2", p)
    self._get_accumulator("beta_pow", p, init=jnp.zeros((), jnp.float32))


def _mat_adagrad(self, p):
    self._get_accumulator(
        "moment", p, init=jnp.full(p._data.shape, self._initial,
                                   jnp.float32 if self._use_master(p)
                                   else p._data.dtype))


def _mat_rmsprop(self, p):
    self._get_accumulator("mean_square", p)
    self._get_accumulator("momentum", p)
    if self._centered:
        self._get_accumulator("mean_grad", p)


def _mat_adadelta(self, p):
    self._get_accumulator("avg_squared_grad", p)
    self._get_accumulator("avg_squared_update", p)


def _mat_adamax(self, p):
    self._get_accumulator("moment", p)
    self._get_accumulator("inf_norm", p)
    self._get_accumulator("beta1_pow", p, init=jnp.ones((), jnp.float32))


Momentum._materialize_param = _mat_momentum
Adam._materialize_param = _mat_adam        # AdamW and Lamb share the layout
Lamb._materialize_param = _mat_adam
Adagrad._materialize_param = _mat_adagrad
RMSProp._materialize_param = _mat_rmsprop
Adadelta._materialize_param = _mat_adadelta
Adamax._materialize_param = _mat_adamax


class Rprop(Optimizer):
    """Reference: python/paddle/optimizer/rprop.py (phi rprop_kernel.cc):
    per-element adaptive step sizes — sign agreement with the previous
    grad grows the element's lr by eta+, disagreement shrinks it by eta-
    and suppresses the step, always clipped to learning_rate_range."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        if not (0.0 < learning_rate_range[0] <= learning_rate
                <= learning_rate_range[1]):
            raise ValueError(
                "'0.0 < learning_rate_range[0] <= learning_rate <= "
                "learning_rate_range[1]' must be true")
        if not 0.0 < etas[0] < 1.0 < etas[1]:
            raise ValueError("'0.0 < etas[0] < 1.0 < etas[1]' must be true")
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas
        self._initial_lr = learning_rate

    def _update(self, p, w, g, lr, group):
        prev = self._get_accumulator("prev", p)
        lrs = self._get_accumulator(
            "learning_rates", p,
            init=jnp.full(p._data.shape, self._initial_lr, jnp.float32))
        prod = g * prev
        eta = jnp.where(prod > 0, self._eta_plus,
                        jnp.where(prod < 0, self._eta_minus, 1.0))
        g_eff = jnp.where(prod < 0, 0.0, g)
        lrs = jnp.clip(lrs * eta, self._lr_min, self._lr_max)
        self._set_accumulator("prev", p, g_eff)
        self._set_accumulator("learning_rates", p, lrs)
        return w - jnp.sign(g_eff) * lrs


class LBFGS(Optimizer):
    """Reference: python/paddle/optimizer/lbfgs.py — limited-memory BFGS
    with closure-driven step() and optional strong-Wolfe line search.
    Host-driven like the reference (the python loop IS the algorithm; the
    closure's forward/backward runs on device)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, multi_precision, name)
        self._max_iter = max_iter
        self._max_eval = max_eval or max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None|'strong_wolfe'")
        self._line_search = line_search_fn
        self._state = {"old_dirs": [], "old_stps": [], "ro": [],
                       "prev_flat_grad": None, "H_diag": 1.0,
                       "n_evals": 0}

    # -- flat-vector helpers --
    def _gather(self, what):
        parts = []
        for p in self._parameter_list:
            a = p._grad if what == "grad" else p._data
            parts.append(jnp.ravel(jnp.asarray(
                a if a is not None else jnp.zeros_like(p._data),
                jnp.float32)))
        return jnp.concatenate(parts) if parts else jnp.zeros((0,))

    def _scatter_add(self, flat, alpha):
        off = 0
        for p in self._parameter_list:
            n = p._data.size
            upd = flat[off:off + n].reshape(p._data.shape)
            p._data = (p._data.astype(jnp.float32)
                       + alpha * upd).astype(p._data.dtype)
            off += n

    def _evaluate(self, closure, flat_x0, d, t):
        self._scatter_add(d, t)
        loss = float(np.asarray(closure()._data))
        grad = self._gather("grad")
        # restore x0 exactly
        off = 0
        for p in self._parameter_list:
            n = p._data.size
            p._data = flat_x0[off:off + n].reshape(p._data.shape) \
                .astype(p._data.dtype)
            off += n
        self._state["n_evals"] += 1
        return loss, grad

    def step(self, closure=None):
        if closure is None:
            raise RuntimeError(
                "LBFGS.step requires a closure that re-evaluates the "
                "model, calls loss.backward() and returns the loss "
                "(reference lbfgs.py)")
        st = self._state
        lr = self.get_lr()
        loss = float(np.asarray(closure()._data))
        flat_grad = self._gather("grad")
        if float(jnp.abs(flat_grad).max()) <= self._tol_grad:
            return loss

        for _ in range(self._max_iter):
            # -- direction: two-loop recursion over (s, y) history
            if st["prev_flat_grad"] is None:
                d = -flat_grad
                st["H_diag"] = 1.0
            else:
                y = flat_grad - st["prev_flat_grad"]
                s = st["last_step"]
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:
                    if len(st["old_dirs"]) >= self._history_size:
                        st["old_dirs"].pop(0)
                        st["old_stps"].pop(0)
                        st["ro"].pop(0)
                    st["old_dirs"].append(y)
                    st["old_stps"].append(s)
                    st["ro"].append(1.0 / ys)
                    st["H_diag"] = ys / float(jnp.dot(y, y))
                q = -flat_grad
                al = []
                for s_i, y_i, ro_i in zip(reversed(st["old_stps"]),
                                          reversed(st["old_dirs"]),
                                          reversed(st["ro"])):
                    a_i = ro_i * float(jnp.dot(s_i, q))
                    al.append(a_i)
                    q = q - a_i * y_i
                d = q * st["H_diag"]
                for (s_i, y_i, ro_i), a_i in zip(
                        zip(st["old_stps"], st["old_dirs"], st["ro"]),
                        reversed(al)):
                    b_i = ro_i * float(jnp.dot(y_i, d))
                    d = d + s_i * (a_i - b_i)
            st["prev_flat_grad"] = flat_grad

            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self._tol_change:
                break
            t = min(1.0, 1.0 / float(jnp.abs(flat_grad).sum())) * lr \
                if not st["old_dirs"] else lr

            if self._line_search == "strong_wolfe":
                x0 = self._gather("param")
                t, loss, flat_grad = self._strong_wolfe(
                    closure, x0, t, d, loss, flat_grad, gtd)
                self._scatter_add(d, t)
            else:
                self._scatter_add(d, t)
                loss = float(np.asarray(closure()._data))
                flat_grad = self._gather("grad")
            st["last_step"] = d * t
            if st["n_evals"] >= self._max_eval:
                break
            if float(jnp.abs(flat_grad).max()) <= self._tol_grad:
                break
            if float(jnp.abs(d * t).max()) <= self._tol_change:
                break
        self.clear_grad()
        return loss

    def _strong_wolfe(self, closure, x0, t, d, f0, g0, gtd0,
                      c1=1e-4, c2=0.9, max_ls=25):
        """Strong-Wolfe line search (reference lbfgs.py _strong_wolfe,
        standard bracket + zoom)."""
        f_prev, g_prev, t_prev = f0, g0, 0.0
        f_new, g_new = self._evaluate(closure, x0, d, t)
        for _ in range(max_ls):
            gtd_new = float(jnp.dot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or f_new >= f_prev:
                return self._zoom(closure, x0, d, f0, gtd0, t_prev, t,
                                  f_prev, f_new, c1, c2)
            if abs(gtd_new) <= -c2 * gtd0:
                return t, f_new, g_new
            if gtd_new >= 0:
                return self._zoom(closure, x0, d, f0, gtd0, t, t_prev,
                                  f_new, f_prev, c1, c2)
            t_prev, f_prev, g_prev = t, f_new, g_new
            t = t * 2.0
            f_new, g_new = self._evaluate(closure, x0, d, t)
        return t, f_new, g_new

    def _zoom(self, closure, x0, d, f0, gtd0, lo, hi, f_lo, f_hi, c1, c2,
              max_zoom=25):
        g_best = None
        for _ in range(max_zoom):
            t = 0.5 * (lo + hi)
            f_new, g_new = self._evaluate(closure, x0, d, t)
            if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
                hi, f_hi = t, f_new
            else:
                gtd_new = float(jnp.dot(g_new, d))
                if abs(gtd_new) <= -c2 * gtd0:
                    return t, f_new, g_new
                if gtd_new * (hi - lo) >= 0:
                    hi, f_hi = lo, f_lo
                lo, f_lo, g_best = t, f_new, g_new
            if abs(hi - lo) < 1e-9:
                break
        if g_best is None:
            f_new, g_best = self._evaluate(closure, x0, d, lo)
            return lo, f_new, g_best
        return lo, f_lo, g_best
