"""Vision datasets (reference: python/paddle/vision/datasets/).

This environment has no network egress, so ``download=True`` raises with
instructions; the loaders read the standard on-disk formats (IDX for MNIST,
pickled batches for CIFAR).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress); "
        "place the standard dataset files locally and pass their paths")


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad IDX image magic {magic}"
        data = np.frombuffer(f.read(), np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad IDX label magic {magic}"
        return np.frombuffer(f.read(), np.uint8).astype(np.int64)


class MNIST(Dataset):
    """Reference: vision/datasets/mnist.py (IDX file format)."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path is None or label_path is None:
            if download:
                _no_download(self.NAME)
            raise ValueError(
                f"{self.NAME} requires image_path and label_path to local "
                "IDX files (train-images-idx3-ubyte[.gz] etc.)")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        assert len(self.images) == len(self.labels)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class _CifarBase(Dataset):
    NAME = "Cifar10"
    LABEL_KEY = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            if download:
                _no_download(self.NAME)
            raise ValueError(
                f"{self.NAME} requires data_file pointing at the local "
                "python-version batch file(s)")
        files = data_file if isinstance(data_file, (list, tuple)) \
            else [data_file]
        xs, ys = [], []
        for fp in files:
            with open(fp, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8)
                      .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            ys.append(np.asarray(d[self.LABEL_KEY], np.int64))
        self.images = np.concatenate(xs)
        self.labels = np.concatenate(ys)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    pass


class Cifar100(_CifarBase):
    NAME = "Cifar100"
    LABEL_KEY = b"fine_labels"
