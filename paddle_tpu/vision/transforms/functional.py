"""paddle.vision.transforms.functional — image transform primitives.

Reference: python/paddle/vision/transforms/functional.py (+ the
functional_pil/functional_tensor backends it dispatches to). Host-side
image ops by design (they run in DataLoader workers, as in the reference);
inputs may be PIL images, numpy HWC arrays, or CHW Tensors; output type
follows input type.
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor

__all__ = ["to_tensor", "resize", "pad", "crop", "center_crop", "hflip",
           "vflip", "adjust_brightness", "adjust_contrast",
           "adjust_saturation", "adjust_hue", "affine", "rotate",
           "perspective", "to_grayscale", "normalize", "erase"]


def _to_hwc(img):
    """-> (numpy HWC float or uint8, restore_fn)."""
    try:
        from PIL import Image
        if isinstance(img, Image.Image):
            arr = np.asarray(img)
            return arr, lambda a: Image.fromarray(
                np.clip(a, 0, 255).astype(np.uint8))
    except ImportError:
        pass
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
        if arr.ndim == 3 and arr.shape[0] in (1, 3, 4):  # CHW
            return arr.transpose(1, 2, 0), \
                lambda a: Tensor(np.ascontiguousarray(
                    a.transpose(2, 0, 1)).astype(arr.dtype))
        return arr, lambda a: Tensor(a.astype(arr.dtype))
    arr = np.asarray(img)
    return arr, lambda a: a.astype(arr.dtype) if a.dtype != arr.dtype else a


def to_tensor(pic, data_format="CHW"):
    from . import to_tensor as _tt
    return _tt(pic, data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from . import normalize as _n
    return _n(img, mean, std, data_format)


def resize(img, size, interpolation="bilinear"):
    from . import resize as _r
    return _r(img, size, interpolation)


def center_crop(img, output_size):
    from . import center_crop as _c
    return _c(img, output_size)


def hflip(img):
    arr, back = _to_hwc(img)
    return back(arr[:, ::-1].copy())


def vflip(img):
    arr, back = _to_hwc(img)
    return back(arr[::-1].copy())


def crop(img, top, left, height, width):
    arr, back = _to_hwc(img)
    return back(arr[top:top + height, left:left + width].copy())


def pad(img, padding, fill=0, padding_mode="constant"):
    """padding: int | [pad_left, pad_right] | [l, t, r, b]."""
    arr, back = _to_hwc(img)
    if isinstance(padding, int):
        l = t = r = b = padding
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        out = np.pad(arr, pads, mode="constant", constant_values=fill)
    else:
        mode = {"reflect": "reflect", "edge": "edge",
                "symmetric": "symmetric"}[padding_mode]
        out = np.pad(arr, pads, mode=mode)
    return back(out)


def adjust_brightness(img, brightness_factor):
    arr, back = _to_hwc(img)
    return back(np.clip(arr.astype(np.float32) * brightness_factor, 0,
                        255 if arr.dtype == np.uint8 else np.inf))


def adjust_contrast(img, contrast_factor):
    arr, back = _to_hwc(img)
    f = arr.astype(np.float32)
    gray_mean = f.mean() if f.ndim == 2 or f.shape[-1] == 1 else \
        (f[..., :3] @ np.array([0.299, 0.587, 0.114],
                               np.float32)).mean()
    out = gray_mean + contrast_factor * (f - gray_mean)
    return back(np.clip(out, 0, 255 if arr.dtype == np.uint8 else np.inf))


def adjust_saturation(img, saturation_factor):
    arr, back = _to_hwc(img)
    f = arr.astype(np.float32)
    gray = f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)
    out = gray[..., None] + saturation_factor * (f - gray[..., None])
    return back(np.clip(out, 0, 255 if arr.dtype == np.uint8 else np.inf))


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5] — shift the H channel in HSV space."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr, back = _to_hwc(img)
    f = arr.astype(np.float32)
    scale = 255.0 if arr.dtype == np.uint8 else 1.0
    rgb = f[..., :3] / scale
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    h = np.zeros_like(mx)
    mask = diff > 0
    rmax = mask & (mx == r)
    gmax = mask & (mx == g) & ~rmax
    bmax = mask & ~rmax & ~gmax
    h[rmax] = ((g - b)[rmax] / diff[rmax]) % 6
    h[gmax] = (b - r)[gmax] / diff[gmax] + 2
    h[bmax] = (r - g)[bmax] / diff[bmax] + 4
    h = (h / 6 + hue_factor) % 1.0
    # hsv -> rgb
    v = mx
    s = np.where(mx > 0, diff / np.where(mx > 0, mx, 1), 0)
    i = np.floor(h * 6)
    fr = h * 6 - i
    p = v * (1 - s)
    q = v * (1 - fr * s)
    t = v * (1 - (1 - fr) * s)
    i = i.astype(np.int32) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q])], axis=-1) * scale
    if f.shape[-1] > 3:
        out = np.concatenate([out, f[..., 3:]], axis=-1)
    return back(np.clip(out, 0, 255 if arr.dtype == np.uint8 else np.inf))


def _sample_affine(arr, mat, fill=0, interpolation="nearest"):
    """Inverse-warp sampling with a 3x3 matrix mapping OUTPUT -> INPUT."""
    H, W = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype(np.float32)
    src = mat @ coords
    sx = src[0] / np.where(src[2] == 0, 1, src[2])
    sy = src[1] / np.where(src[2] == 0, 1, src[2])
    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = sx - x0
        wy = sy - y0
        out = np.zeros((H * W,) + arr.shape[2:], np.float32)
        valid_any = np.zeros(H * W, bool)
        for dy, wyv in ((0, 1 - wy), (1, wy)):
            for dx, wxv in ((0, 1 - wx), (1, wx)):
                xi, yi = x0 + dx, y0 + dy
                ok = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
                valid_any |= ok
                w = (wxv * wyv)[ok]
                if arr.ndim == 3:
                    w = w[:, None]
                out[ok] += w * arr[yi[ok].clip(0, H - 1),
                                   xi[ok].clip(0, W - 1)].astype(np.float32)
        out[~valid_any] = fill
    else:
        xi = np.round(sx).astype(np.int64)
        yi = np.round(sy).astype(np.int64)
        ok = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
        out = np.full((H * W,) + arr.shape[2:], fill, np.float32)
        out[ok] = arr[yi[ok], xi[ok]]
    return out.reshape(arr.shape)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Reference: functional.affine — rotation/translation/scale/shear
    about the image center (or ``center``)."""
    arr, back = _to_hwc(img)
    H, W = arr.shape[:2]
    cx, cy = center if center is not None else ((W - 1) * 0.5,
                                                (H - 1) * 0.5)
    rot = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in (shear if isinstance(
        shear, (list, tuple)) else (shear, 0.0))]
    # forward matrix (center-relative), reference _get_affine_matrix
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    fwd = np.array([[scale * a, scale * b, 0],
                    [scale * c, scale * d, 0],
                    [0, 0, 1]], np.float32)
    tx, ty = translate
    pre = np.array([[1, 0, cx + tx], [0, 1, cy + ty], [0, 0, 1]],
                   np.float32)
    post = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float32)
    m = pre @ fwd @ post
    inv = np.linalg.inv(m)
    return back(_sample_affine(arr, inv, fill, interpolation))


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr, back = _to_hwc(img)
    if expand:
        H, W = arr.shape[:2]
        rot = np.deg2rad(angle)
        nw = int(np.ceil(abs(W * np.cos(rot)) + abs(H * np.sin(rot))))
        nh = int(np.ceil(abs(W * np.sin(rot)) + abs(H * np.cos(rot))))
        # rotate on a canvas big enough both ways, then crop to (nh, nw)
        sh, sw = max(nh, H), max(nw, W)
        padded = np.zeros((sh, sw) + arr.shape[2:], arr.dtype)
        oy, ox = (sh - H) // 2, (sw - W) // 2
        padded[oy:oy + H, ox:ox + W] = arr
        rotated = affine(padded, angle, (0, 0), 1.0, (0.0, 0.0),
                         interpolation, fill, None)
        cy, cx = (sh - nh) // 2, (sw - nw) // 2
        return back(np.asarray(rotated)[cy:cy + nh, cx:cx + nw])
    return affine(img, angle, (0, 0), 1.0, (0.0, 0.0), interpolation,
                  fill, center)


def _get_perspective_coeffs(startpoints, endpoints):
    """Solve the 8-dof homography endpoints -> startpoints (reference
    functional.py:811)."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b += [sx, sy]
    coeffs = np.linalg.solve(np.asarray(a, np.float32),
                             np.asarray(b, np.float32))
    return coeffs


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    arr, back = _to_hwc(img)
    co = _get_perspective_coeffs(startpoints, endpoints)
    m = np.array([[co[0], co[1], co[2]],
                  [co[3], co[4], co[5]],
                  [co[6], co[7], 1.0]], np.float32)
    return back(_sample_affine(arr, m, fill, interpolation))


def to_grayscale(img, num_output_channels=1):
    arr, back = _to_hwc(img)
    f = arr.astype(np.float32)
    gray = f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return back(out)


def erase(img, i, j, h, w, v, inplace=False):
    """Reference: functional.erase — fill box [i:i+h, j:j+w] with v.
    Tensor input is CHW (fills [:, i:i+h, j:j+w])."""
    if isinstance(img, Tensor):
        arr = np.asarray(img._data).copy()
        arr[..., i:i + h, j:j + w] = np.asarray(v, arr.dtype)
        if inplace:
            import jax.numpy as jnp
            img._data = jnp.asarray(arr)
            return img
        return Tensor(arr)
    arr, back = _to_hwc(img)
    out = arr.copy()
    out[i:i + h, j:j + w] = v
    return back(out)
