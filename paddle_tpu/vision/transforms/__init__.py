"""Vision transforms (reference: python/paddle/vision/transforms/).

Host-side numpy pipeline (runs in DataLoader workers) — images are HWC uint8
or float arrays; ToTensor produces CHW float32 Tensors.
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "to_tensor", "normalize",
           "resize", "hflip", "center_crop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def to_tensor(img, data_format="CHW"):
    arr = np.asarray(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW"):
    arr = img.numpy() if isinstance(img, Tensor) else np.asarray(
        img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = [mean] if np.isscalar(mean) else list(mean)
        self.std = [std] if np.isscalar(std) else list(std)
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    # nearest/bilinear resize without PIL: index-map (nearest) or lerp
    yi = np.linspace(0, h - 1, oh)
    xi = np.linspace(0, w - 1, ow)
    if interpolation == "nearest":
        out = arr[np.round(yi).astype(int)][:, np.round(xi).astype(int)]
    else:
        y0 = np.floor(yi).astype(int)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x0 = np.floor(xi).astype(int)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = (yi - y0)[:, None]
        wx = (xi - x0)[None, :]
        if arr.ndim == 3:
            wy = wy[..., None]
            wx = wx[..., None]
        a = arr[y0][:, x0].astype(np.float32)
        b = arr[y0][:, x1].astype(np.float32)
        c = arr[y1][:, x0].astype(np.float32)
        d = arr[y1][:, x1].astype(np.float32)
        out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
               + c * wy * (1 - wx) + d * wy * wx)
        if arr.dtype == np.uint8:
            out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def center_crop(img, output_size):
    arr = np.asarray(img)
    th, tw = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    h, w = arr.shape[:2]
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return arr[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=0, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            pads = [(self.padding, self.padding),
                    (self.padding, self.padding)] + \
                ([(0, 0)] if arr.ndim == 3 else [])
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i:i + th, j:j + tw]


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img).astype(np.float32)
        factor = 1 + np.random.uniform(-self.value, self.value)
        out = arr * factor
        if np.asarray(img).dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


# functional submodule (reference: vision/transforms/functional.py); its
# primitives are also reachable at the transforms level like the reference
from . import functional  # noqa: E402,F401
from .functional import (  # noqa: E402,F401
    adjust_brightness, adjust_contrast, adjust_hue, adjust_saturation,
    affine, crop, erase, pad, perspective, rotate, to_grayscale, vflip,
)
