"""paddle.vision.ops — detection ops (nms, roi_align, box utilities).

Reference: python/paddle/vision/ops.py (nms over phi nms_kernel, roi_align
over roi_align_kernel). TPU notes: nms's data-dependent suppression loop is
a lax.while-style fixed-point over a static-size score order (compiled
control flow, no host sync); roi_align is a vectorized bilinear gather —
XLA turns it into one fused gather/interpolate program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["nms", "roi_align", "box_area", "box_iou"]


def _pairwise_iou(a, b):
    """IoU matrix [len(a), len(b)] — the single source of the box math."""
    ar1 = jnp.maximum(a[:, 2] - a[:, 0], 0) \
        * jnp.maximum(a[:, 3] - a[:, 1], 0)
    ar2 = jnp.maximum(b[:, 2] - b[:, 0], 0) \
        * jnp.maximum(b[:, 3] - b[:, 1], 0)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    return inter / jnp.maximum(ar1[:, None] + ar2[None, :] - inter, 1e-10)


def _iou_matrix(boxes):
    return _pairwise_iou(boxes, boxes)


def box_area(boxes):
    return apply("box_area", lambda b: jnp.maximum(b[:, 2] - b[:, 0], 0)
                 * jnp.maximum(b[:, 3] - b[:, 1], 0), [boxes])


def box_iou(boxes1, boxes2):
    return apply("box_iou", _pairwise_iou, [boxes1, boxes2])


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Reference: vision/ops.py nms. boxes [N, 4] (x1,y1,x2,y2); returns
    kept indices sorted by score. Category-aware when category_idxs given
    (boxes of different categories never suppress each other).

    TPU-native: greedy suppression as a compiled sequential scan over the
    score-sorted boxes (the dependency is inherently sequential; the IoU
    matrix is computed once on the MXU-friendly vectorized path)."""
    n = boxes.shape[0]

    def f(b, *rest):
        sc = rest[0] if scores is not None else jnp.arange(
            n, 0, -1, dtype=jnp.float32)
        order = jnp.argsort(-sc)
        bs = b[order]
        iou = _iou_matrix(bs)
        if category_idxs is not None:
            cat = rest[-1][order]
            same = cat[:, None] == cat[None, :]
            iou = jnp.where(same, iou, 0.0)

        def step(keep, i):
            # suppressed if any higher-scored KEPT box overlaps too much
            over = (iou[i] > iou_threshold) & keep \
                & (jnp.arange(n) < i)
            ki = ~jnp.any(over)
            return keep.at[i].set(ki), None

        keep, _ = jax.lax.scan(step, jnp.zeros(n, bool), jnp.arange(n))
        kept_sorted = jnp.where(keep, jnp.arange(n), n)
        sel = jnp.sort(kept_sorted)  # positions in score order
        return order[jnp.clip(sel, 0, n - 1)], jnp.sum(keep)

    # selection indices are not differentiable — detach so the dispatch
    # never tapes integer outputs (dispatch aux convention)
    ins = [boxes.detach()]
    if scores is not None:
        ins.append(scores.detach())
    if category_idxs is not None:
        ins.append(category_idxs)
    idxs, count = apply("nms", lambda *a: f(*a), ins, nout=2)
    c = int(count.numpy())
    out = idxs[:c]
    if top_k is not None:
        out = out[:min(top_k, c)]
    return out


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference: vision/ops.py roi_align (phi roi_align_kernel).
    x [N, C, H, W]; boxes [R, 4] in input coords; boxes_num [N] rois per
    image. Returns [R, C, out_h, out_w].

    Deviation from reference when sampling_ratio <= 0: the reference picks
    a per-ROI adaptive sample count ceil(roi/output); XLA needs one static
    count, so this uses the max over all (concrete) ROIs — small ROIs are
    sampled denser than the reference, so their bin averages can differ by
    O(1e-2) when ROI sizes vary. Pass an explicit sampling_ratio for exact
    reference parity."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    if sampling_ratio <= 0:
        # reference adapts per-roi (ceil(roi/output)); XLA needs a static
        # count, so use the max over the (eager, concrete) rois — under
        # tracing fall back to 2 samples/bin
        try:
            import numpy as _np
            rnp = _np.asarray(boxes._data if isinstance(boxes, Tensor)
                              else boxes)
            mx = max(float((rnp[:, 2] - rnp[:, 0]).max()) / ow,
                     float((rnp[:, 3] - rnp[:, 1]).max()) / oh)
            sampling_ratio = max(1, int(np.ceil(mx * spatial_scale)))
        except Exception:
            sampling_ratio = 2

    def f(feat, rois, rois_num):
        N, C, H, W = feat.shape
        R = rois.shape[0]
        # map each roi to its image
        img_idx = jnp.repeat(jnp.arange(N), rois_num, axis=0,
                             total_repeat_length=R)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w = rw / ow
        bin_h = rh / oh
        sr = sampling_ratio
        # sample grid: [R, oh, ow, sr, sr] bilinear points, averaged per bin
        iy = jnp.arange(oh)
        ix = jnp.arange(ow)
        sy = (jnp.arange(sr) + 0.5) / sr
        sx = (jnp.arange(sr) + 0.5) / sr
        yy = (y1[:, None, None] + (iy[None, :, None] + sy[None, None, :])
              * bin_h[:, None, None])                       # [R, oh, sr]
        xx = (x1[:, None, None] + (ix[None, :, None] + sx[None, None, :])
              * bin_w[:, None, None])                       # [R, ow, sr]

        def bilinear(py, px):
            # py [R, oh, sr] / px [R, ow, sr] -> [R, C, oh, sr, ow, sr]
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy1 = py - y0
            wx1 = px - x0

            def gath(yi, xi):
                yc = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
                xc = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
                # feat[img, :, y, x] over broadcasted roi grids
                return feat[img_idx[:, None, None, None, None], :,
                            yc[:, :, :, None, None],
                            xc[:, None, None, :, :]]

            # gather corners: shapes [R, oh, sr, ow, sr, C]
            g00 = gath(y0, x0)
            g01 = gath(y0, x0 + 1)
            g10 = gath(y0 + 1, x0)
            g11 = gath(y0 + 1, x0 + 1)
            wy1e = wy1[:, :, :, None, None, None]
            wx1e = wx1[:, None, None, :, :, None]
            return (g00 * (1 - wy1e) * (1 - wx1e)
                    + g01 * (1 - wy1e) * wx1e
                    + g10 * wy1e * (1 - wx1e)
                    + g11 * wy1e * wx1e)

        samples = bilinear(yy, xx)              # [R, oh, sr, ow, sr, C]
        # reference kernel zeroes samples outside [-1, H] x [-1, W]
        # (replicate-clamp only applies within one pixel of the border)
        ok_y = (yy >= -1.0) & (yy <= H)
        ok_x = (xx >= -1.0) & (xx <= W)
        mask = (ok_y[:, :, :, None, None]
                & ok_x[:, None, None, :, :])[..., None]
        samples = jnp.where(mask, samples, 0.0)
        pooled = samples.mean(axis=(2, 4))      # [R, oh, ow, C]
        return jnp.transpose(pooled, (0, 3, 1, 2))

    return apply("roi_align", f, [x, boxes, boxes_num])
