"""paddle.vision.ops — detection ops (nms, roi_align, box utilities).

Reference: python/paddle/vision/ops.py (nms over phi nms_kernel, roi_align
over roi_align_kernel). TPU notes: nms's data-dependent suppression loop is
a lax.while-style fixed-point over a static-size score order (compiled
control flow, no host sync); roi_align is a vectorized bilinear gather —
XLA turns it into one fused gather/interpolate program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["nms", "roi_align", "box_area", "box_iou"]


def _pairwise_iou(a, b):
    """IoU matrix [len(a), len(b)] — the single source of the box math."""
    ar1 = jnp.maximum(a[:, 2] - a[:, 0], 0) \
        * jnp.maximum(a[:, 3] - a[:, 1], 0)
    ar2 = jnp.maximum(b[:, 2] - b[:, 0], 0) \
        * jnp.maximum(b[:, 3] - b[:, 1], 0)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    return inter / jnp.maximum(ar1[:, None] + ar2[None, :] - inter, 1e-10)


def _iou_matrix(boxes):
    return _pairwise_iou(boxes, boxes)


def box_area(boxes):
    return apply("box_area", lambda b: jnp.maximum(b[:, 2] - b[:, 0], 0)
                 * jnp.maximum(b[:, 3] - b[:, 1], 0), [boxes])


def box_iou(boxes1, boxes2):
    return apply("box_iou", _pairwise_iou, [boxes1, boxes2])


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Reference: vision/ops.py nms. boxes [N, 4] (x1,y1,x2,y2); returns
    kept indices sorted by score. Category-aware when category_idxs given
    (boxes of different categories never suppress each other).

    TPU-native: greedy suppression as a compiled sequential scan over the
    score-sorted boxes (the dependency is inherently sequential; the IoU
    matrix is computed once on the MXU-friendly vectorized path)."""
    n = boxes.shape[0]

    def f(b, *rest):
        sc = rest[0] if scores is not None else jnp.arange(
            n, 0, -1, dtype=jnp.float32)
        order = jnp.argsort(-sc)
        bs = b[order]
        iou = _iou_matrix(bs)
        if category_idxs is not None:
            cat = rest[-1][order]
            same = cat[:, None] == cat[None, :]
            iou = jnp.where(same, iou, 0.0)

        def step(keep, i):
            # suppressed if any higher-scored KEPT box overlaps too much
            over = (iou[i] > iou_threshold) & keep \
                & (jnp.arange(n) < i)
            ki = ~jnp.any(over)
            return keep.at[i].set(ki), None

        keep, _ = jax.lax.scan(step, jnp.zeros(n, bool), jnp.arange(n))
        kept_sorted = jnp.where(keep, jnp.arange(n), n)
        sel = jnp.sort(kept_sorted)  # positions in score order
        return order[jnp.clip(sel, 0, n - 1)], jnp.sum(keep)

    # selection indices are not differentiable — detach so the dispatch
    # never tapes integer outputs (dispatch aux convention)
    ins = [boxes.detach()]
    if scores is not None:
        ins.append(scores.detach())
    if category_idxs is not None:
        ins.append(category_idxs)
    idxs, count = apply("nms", lambda *a: f(*a), ins, nout=2)
    c = int(count.numpy())
    out = idxs[:c]
    if top_k is not None:
        out = out[:min(top_k, c)]
    return out


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference: vision/ops.py roi_align (phi roi_align_kernel).
    x [N, C, H, W]; boxes [R, 4] in input coords; boxes_num [N] rois per
    image. Returns [R, C, out_h, out_w].

    Deviation from reference when sampling_ratio <= 0: the reference picks
    a per-ROI adaptive sample count ceil(roi/output); XLA needs one static
    count, so this uses the max over all (concrete) ROIs — small ROIs are
    sampled denser than the reference, so their bin averages can differ by
    O(1e-2) when ROI sizes vary. Pass an explicit sampling_ratio for exact
    reference parity."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    if sampling_ratio <= 0:
        # reference adapts per-roi (ceil(roi/output)); XLA needs a static
        # count, so use the max over the (eager, concrete) rois — under
        # tracing fall back to 2 samples/bin
        try:
            import numpy as _np
            rnp = _np.asarray(boxes._data if isinstance(boxes, Tensor)
                              else boxes)
            mx = max(float((rnp[:, 2] - rnp[:, 0]).max()) / ow,
                     float((rnp[:, 3] - rnp[:, 1]).max()) / oh)
            sampling_ratio = max(1, int(np.ceil(mx * spatial_scale)))
        except Exception:
            sampling_ratio = 2

    def f(feat, rois, rois_num):
        N, C, H, W = feat.shape
        R = rois.shape[0]
        # map each roi to its image
        img_idx = jnp.repeat(jnp.arange(N), rois_num, axis=0,
                             total_repeat_length=R)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w = rw / ow
        bin_h = rh / oh
        sr = sampling_ratio
        # sample grid: [R, oh, ow, sr, sr] bilinear points, averaged per bin
        iy = jnp.arange(oh)
        ix = jnp.arange(ow)
        sy = (jnp.arange(sr) + 0.5) / sr
        sx = (jnp.arange(sr) + 0.5) / sr
        yy = (y1[:, None, None] + (iy[None, :, None] + sy[None, None, :])
              * bin_h[:, None, None])                       # [R, oh, sr]
        xx = (x1[:, None, None] + (ix[None, :, None] + sx[None, None, :])
              * bin_w[:, None, None])                       # [R, ow, sr]

        def bilinear(py, px):
            # py [R, oh, sr] / px [R, ow, sr] -> [R, C, oh, sr, ow, sr]
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy1 = py - y0
            wx1 = px - x0

            def gath(yi, xi):
                yc = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
                xc = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
                # feat[img, :, y, x] over broadcasted roi grids
                return feat[img_idx[:, None, None, None, None], :,
                            yc[:, :, :, None, None],
                            xc[:, None, None, :, :]]

            # gather corners: shapes [R, oh, sr, ow, sr, C]
            g00 = gath(y0, x0)
            g01 = gath(y0, x0 + 1)
            g10 = gath(y0 + 1, x0)
            g11 = gath(y0 + 1, x0 + 1)
            wy1e = wy1[:, :, :, None, None, None]
            wx1e = wx1[:, None, None, :, :, None]
            return (g00 * (1 - wy1e) * (1 - wx1e)
                    + g01 * (1 - wy1e) * wx1e
                    + g10 * wy1e * (1 - wx1e)
                    + g11 * wy1e * wx1e)

        samples = bilinear(yy, xx)              # [R, oh, sr, ow, sr, C]
        # reference kernel zeroes samples outside [-1, H] x [-1, W]
        # (replicate-clamp only applies within one pixel of the border)
        ok_y = (yy >= -1.0) & (yy <= H)
        ok_x = (xx >= -1.0) & (xx <= W)
        mask = (ok_y[:, :, :, None, None]
                & ok_x[:, None, None, :, :])[..., None]
        samples = jnp.where(mask, samples, 0.0)
        pooled = samples.mean(axis=(2, 4))      # [R, oh, ow, C]
        return jnp.transpose(pooled, (0, 3, 1, 2))

    return apply("roi_align", f, [x, boxes, boxes_num])


# ---------------------------------------------------------------------------
# Detection op family (reference: python/paddle/vision/ops.py over
# phi/kernels roi_pool/psroi_pool/deform_conv/yolo_box/... kernels).
# Dense sampling math runs as jnp taped ops; proposal-style ops with
# data-dependent output counts (generate_proposals, matrix_nms) run on the
# host like the reference's CPU kernels — their outputs are ragged by
# nature and feed host-side dataloaders, not jitted training steps.
# ---------------------------------------------------------------------------

def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Reference: vision/ops.py roi_pool (quantized max pooling per bin).
    x [N, C, H, W]; boxes [R, 4]; returns [R, C, oh, ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, rois, rois_num):
        N, C, H, W = feat.shape
        R = rois.shape[0]
        img_idx = jnp.repeat(jnp.arange(N), rois_num, axis=0,
                             total_repeat_length=R)
        scaled = jnp.round(rois * spatial_scale)
        x1, y1 = scaled[:, 0], scaled[:, 1]
        x2, y2 = jnp.maximum(scaled[:, 2], x1 + 1), \
            jnp.maximum(scaled[:, 3], y1 + 1)
        rw, rh = x2 - x1, y2 - y1
        neg = jnp.asarray(-jnp.inf, feat.dtype)
        ys = jnp.arange(H, dtype=feat.dtype)
        xs = jnp.arange(W, dtype=feat.dtype)
        roi_feat = feat[img_idx]                   # [R, C, H, W] — hoisted
        outs = []
        for by in range(oh):
            for bx in range(ow):
                ys0 = y1 + jnp.floor(rh * by / oh)
                ys1 = y1 + jnp.ceil(rh * (by + 1) / oh)
                xs0 = x1 + jnp.floor(rw * bx / ow)
                xs1 = x1 + jnp.ceil(rw * (bx + 1) / ow)
                inside = ((ys[None, :] >= ys0[:, None])
                          & (ys[None, :] < ys1[:, None]))[:, None, :, None] \
                    & ((xs[None, :] >= xs0[:, None])
                       & (xs[None, :] < xs1[:, None]))[:, None, None, :]
                masked = jnp.where(inside, roi_feat, neg)
                outs.append(masked.max(axis=(2, 3)))
        out = jnp.stack(outs, axis=-1).reshape(R, C, oh, ow)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return apply("roi_pool", f, [x, boxes, boxes_num])


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Reference: vision/ops.py psroi_pool — position-sensitive average
    pooling: input channels C = out_c*oh*ow, bin (i,j) reads channel slice
    [(i*ow+j)*out_c : ...]. Returns [R, out_c, oh, ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, rois, rois_num):
        N, C, H, W = feat.shape
        assert C % (oh * ow) == 0, (
            f"psroi_pool needs channels ({C}) divisible by "
            f"output_size bins ({oh}x{ow})")
        out_c = C // (oh * ow)
        R = rois.shape[0]
        img_idx = jnp.repeat(jnp.arange(N), rois_num, axis=0,
                             total_repeat_length=R)
        scaled = rois * spatial_scale
        x1, y1, x2, y2 = (scaled[:, i] for i in range(4))
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        ys = jnp.arange(H, dtype=feat.dtype)
        xs = jnp.arange(W, dtype=feat.dtype)
        bins = []
        for by in range(oh):
            for bx in range(ow):
                ys0 = y1 + rh * by / oh
                ys1 = y1 + rh * (by + 1) / oh
                xs0 = x1 + rw * bx / ow
                xs1 = x1 + rw * (bx + 1) / ow
                inside = ((ys[None, :] >= jnp.floor(ys0)[:, None])
                          & (ys[None, :] < jnp.ceil(ys1)[:, None])
                          )[:, None, :, None] \
                    & ((xs[None, :] >= jnp.floor(xs0)[:, None])
                       & (xs[None, :] < jnp.ceil(xs1)[:, None])
                       )[:, None, None, :]
                sl = feat[img_idx,
                          (by * ow + bx) * out_c:(by * ow + bx + 1) * out_c]
                total = jnp.where(inside, sl, 0.0).sum(axis=(2, 3))
                cnt = jnp.maximum(
                    inside.astype(feat.dtype).sum(axis=(2, 3)), 1.0)
                bins.append(total / cnt)
        return jnp.stack(bins, axis=-1).reshape(R, out_c, oh, ow)

    return apply("psroi_pool", f, [x, boxes, boxes_num])


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Reference: vision/ops.py deform_conv2d (DCNv1; DCNv2 when mask is
    given). x [N,Cin,H,W]; offset [N, 2*dg*kh*kw, Ho, Wo] (y,x order per
    kernel point); weight [Cout, Cin/groups, kh, kw].

    TPU-native: bilinear sampling at offset positions is a vectorized
    gather; the conv collapses to one einsum over sampled patches (MXU)."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    has_mask = mask is not None
    has_bias = bias is not None

    def f(xa, off, w, *rest):
        rest = list(rest)
        m = rest.pop(0) if has_mask else None
        b = rest.pop(0) if has_bias else None
        N, Cin, H, W = xa.shape
        Cout, Cg, kh, kw = w.shape
        Ho = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        Wo = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        K = kh * kw
        off = off.reshape(N, deformable_groups, K, 2, Ho, Wo)
        # base sampling grid
        oy = jnp.arange(Ho) * s[0] - p[0]
        ox = jnp.arange(Wo) * s[1] - p[1]
        ky = jnp.arange(kh) * d[0]
        kx = jnp.arange(kw) * d[1]
        base_y = oy[None, :, None] + ky.reshape(kh, 1, 1)   # [kh,Ho,1]
        base_x = ox[None, None, :] + kx.reshape(kw, 1, 1)   # [kw,1,Wo] -> fix
        base_y = base_y.reshape(kh, 1, Ho, 1)
        base_x = base_x.reshape(1, kw, 1, Wo)
        base_y = jnp.broadcast_to(base_y, (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)
        base_x = jnp.broadcast_to(base_x, (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)
        sy = base_y[None, None] + off[:, :, :, 0]   # [N,dg,K,Ho,Wo]
        sx = base_x[None, None] + off[:, :, :, 1]

        def bilinear(img, yy, xx):
            # img [Cin,H,W]; yy/xx [dg,K,Ho,Wo] -> samples [Cin,dg,K,Ho,Wo]
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy = yy - y0
            wx = xx - x0
            out = 0.0
            for dy2, wy2 in ((0, 1 - wy), (1, wy)):
                for dx2, wx2 in ((0, 1 - wx), (1, wx)):
                    yi = y0 + dy2
                    xi = x0 + dx2
                    valid = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
                    yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                    xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                    v = img[:, yc, xc]              # [Cin,dg,K,Ho,Wo]
                    out = out + jnp.where(valid, (wy2 * wx2), 0.0) * v
            return out

        samples = jax.vmap(bilinear)(xa, sy, sx)    # [N,Cin,dg,K,Ho,Wo]
        if m is not None:
            mm = m.reshape(N, deformable_groups, K, Ho, Wo)
            samples = samples * mm[:, None]
        # fold deformable groups back into channels: each input channel
        # belongs to dg group c // (Cin/dg)
        cg = Cin // deformable_groups
        samples = samples.reshape(N, deformable_groups, cg,
                                  deformable_groups, K, Ho, Wo)
        samples = jnp.stack([samples[:, g, :, g] for g in
                             range(deformable_groups)], axis=1)
        samples = samples.reshape(N, Cin, K, Ho, Wo)
        wk = w.reshape(groups, Cout // groups, Cg, K)
        sg = samples.reshape(N, groups, Cg, K, Ho, Wo)
        out = jnp.einsum("ngckyx,gock->ngoyx", sg, wk)  # MXU GEMM
        out = out.reshape(N, Cout, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, Cout, 1, 1)
        return out

    ins = [x, offset, weight]
    if has_mask:
        ins.append(mask)
    if has_bias:
        ins.append(bias)
    return apply("deform_conv2d", f, ins)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Reference: vision/ops.py box_coder (phi box_coder_kernel): encode
    boxes against priors or decode offsets back to boxes."""
    def f(prior, tbox, *rest):
        var = rest[0] if rest else None
        norm = 0.0 if box_normalized else 1.0
        pw = prior[:, 2] - prior[:, 0] + norm
        ph = prior[:, 3] - prior[:, 1] + norm
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tbox[:, None, 2] - tbox[:, None, 0] + norm
            th = tbox[:, None, 3] - tbox[:, None, 1] + norm
            tcx = tbox[:, None, 0] + tw * 0.5
            tcy = tbox[:, None, 1] + th * 0.5
            out = jnp.stack([(tcx - pcx[None]) / pw[None],
                             (tcy - pcy[None]) / ph[None],
                             jnp.log(tw / pw[None]),
                             jnp.log(th / ph[None])], axis=-1)
            if var is not None:
                out = out / (var[None] if var.ndim == 2 else var)
            return out
        # decode_center_size: tbox [N, M, 4]
        dev = tbox
        if var is not None:
            dev = dev * (var[None] if var.ndim == 2 else var)
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (v[None, :] for v in (pw, ph, pcx, pcy))
        else:
            pw_, ph_, pcx_, pcy_ = (v[:, None] for v in (pw, ph, pcx, pcy))
        ocx = dev[..., 0] * pw_ + pcx_
        ocy = dev[..., 1] * ph_ + pcy_
        ow_ = jnp.exp(dev[..., 2]) * pw_
        oh_ = jnp.exp(dev[..., 3]) * ph_
        return jnp.stack([ocx - ow_ * 0.5, ocy - oh_ * 0.5,
                          ocx + ow_ * 0.5 - norm,
                          ocy + oh_ * 0.5 - norm], axis=-1)

    ins = [prior_box, target_box]
    if prior_box_var is not None and not isinstance(prior_box_var, list):
        ins.append(prior_box_var)
    elif isinstance(prior_box_var, list):
        ins.append(Tensor(jnp.asarray(prior_box_var, jnp.float32)))
    return apply("box_coder", f, ins)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """Reference: vision/ops.py prior_box (SSD prior generation).
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    import numpy as onp
    feat_h, feat_w = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_h = steps[1] or img_h / feat_h
    step_w = steps[0] or img_w / feat_w
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            boxes.append((ms, ms))
            if max_sizes:
                mx = max_sizes[ms_i]
                boxes.append((onp.sqrt(ms * mx), onp.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                boxes.append((ms * onp.sqrt(ar), ms / onp.sqrt(ar)))
        else:
            for ar in ars:
                boxes.append((ms * onp.sqrt(ar), ms / onp.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[ms_i]
                boxes.append((onp.sqrt(ms * mx), onp.sqrt(ms * mx)))
    P = len(boxes)
    wh = onp.asarray(boxes, onp.float32)            # [P, 2] (w, h)
    cy = (onp.arange(feat_h) + offset) * step_h
    cx = (onp.arange(feat_w) + offset) * step_w
    cxg, cyg = onp.meshgrid(cx, cy)                 # [H, W]
    out = onp.zeros((feat_h, feat_w, P, 4), onp.float32)
    out[..., 0] = (cxg[:, :, None] - wh[None, None, :, 0] / 2) / img_w
    out[..., 1] = (cyg[:, :, None] - wh[None, None, :, 1] / 2) / img_h
    out[..., 2] = (cxg[:, :, None] + wh[None, None, :, 0] / 2) / img_w
    out[..., 3] = (cyg[:, :, None] + wh[None, None, :, 1] / 2) / img_h
    if clip:
        out = out.clip(0.0, 1.0)
    var = onp.broadcast_to(onp.asarray(variance, onp.float32),
                           out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Reference: vision/ops.py yolo_box (phi yolo_box_kernel): decode
    YOLOv3 head [N, A*(5+cls), H, W] into boxes [N, A*H*W, 4] and scores
    [N, A*H*W, cls]; confidences below conf_thresh zero the scores."""
    A = len(anchors) // 2

    def f(xa, imgs):
        N, C, H, W = xa.shape
        an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
        if iou_aware:
            ious = jax.nn.sigmoid(xa[:, :A].reshape(N, A, 1, H, W))
            xa = xa[:, A:]
        feats = xa.reshape(N, A, 5 + class_num, H, W)
        gx = (jnp.arange(W, dtype=jnp.float32))[None, None, None, :]
        gy = (jnp.arange(H, dtype=jnp.float32))[None, None, :, None]
        bx = (jax.nn.sigmoid(feats[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx) / W
        by = (jax.nn.sigmoid(feats[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy) / H
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        bw = jnp.exp(feats[:, :, 2]) * an[None, :, 0, None, None] / in_w
        bh = jnp.exp(feats[:, :, 3]) * an[None, :, 1, None, None] / in_h
        conf = jax.nn.sigmoid(feats[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * \
                ious[:, :, 0] ** iou_aware_factor
        cls = jax.nn.sigmoid(feats[:, :, 5:]) * conf[:, :, None]
        imgh = imgs[:, 0].reshape(N, 1, 1, 1).astype(jnp.float32)
        imgw = imgs[:, 1].reshape(N, 1, 1, 1).astype(jnp.float32)
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0)
            y1 = jnp.clip(y1, 0)
            x2 = jnp.minimum(x2, imgw - 1)
            y2 = jnp.minimum(y2, imgh - 1)
        keep = (conf > conf_thresh)[:, :, None]        # [N, A, 1, H, W]
        boxes = jnp.stack([x1, y1, x2, y2], axis=2)    # [N, A, 4, H, W]
        boxes = jnp.where(keep, boxes, 0.0)
        scores = jnp.where(keep, cls, 0.0)
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(N, A * H * W, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            N, A * H * W, class_num)
        return boxes, scores

    return apply("yolo_box", f, [x, img_size], nout=2)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """Reference: vision/ops.py yolo_loss (phi yolo_loss_kernel). YOLOv3
    training loss: BCE on xy, L1 on wh, BCE on objectness (with
    ignore_thresh masking of well-matched predictions) and class BCE.
    Simplification vs the CUDA kernel: objectness targets use the best
    anchor per gt (same assignment rule), gt_score defaults to 1."""
    A = len(anchor_mask)

    def f(xa, gbox, glabel, *rest):
        N, C, H, W = xa.shape
        feats = xa.reshape(N, A, 5 + class_num, H, W)
        an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
        an = an_all[jnp.asarray(anchor_mask)]
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        B = gbox.shape[1]
        # gt in [0,1] center-size
        gx, gy, gw, gh = (gbox[..., i] for i in range(4))
        valid = (gw > 0) & (gh > 0)
        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
        # best anchor per gt by wh IoU against ALL anchors (reference rule)
        gwp = gw * in_w
        ghp = gh * in_h
        inter = jnp.minimum(gwp[..., None], an_all[None, None, :, 0]) * \
            jnp.minimum(ghp[..., None], an_all[None, None, :, 1])
        union = gwp[..., None] * ghp[..., None] + \
            an_all[None, None, :, 0] * an_all[None, None, :, 1] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)
        mask_pos = jnp.zeros((N, A, H, W))
        tx = jnp.zeros((N, A, H, W))
        ty = jnp.zeros((N, A, H, W))
        tw = jnp.zeros((N, A, H, W))
        th = jnp.zeros((N, A, H, W))
        tcls = jnp.zeros((N, A, H, W, class_num))
        tscale = jnp.zeros((N, A, H, W))
        bidx = jnp.arange(N)[:, None].repeat(B, 1)
        # map best anchor to local index in anchor_mask (-1 if absent)
        local = jnp.full((an_all.shape[0],), -1, jnp.int32)
        local = local.at[jnp.asarray(anchor_mask, jnp.int32)].set(
            jnp.arange(A, dtype=jnp.int32))
        la = local[best]
        ok = valid & (la >= 0)
        la_c = jnp.clip(la, 0, A - 1)
        scale = 2.0 - gw * gh
        mask_pos = mask_pos.at[bidx, la_c, gj, gi].max(
            jnp.where(ok, 1.0, 0.0))
        tx = tx.at[bidx, la_c, gj, gi].set(
            jnp.where(ok, gx * W - gi, 0.0))
        ty = ty.at[bidx, la_c, gj, gi].set(jnp.where(ok, gy * H - gj, 0.0))
        sel_an = an_all[jnp.clip(best, 0, an_all.shape[0] - 1)]
        tw = tw.at[bidx, la_c, gj, gi].set(
            jnp.where(ok, jnp.log(jnp.maximum(gwp / sel_an[..., 0], 1e-9)),
                      0.0))
        th = th.at[bidx, la_c, gj, gi].set(
            jnp.where(ok, jnp.log(jnp.maximum(ghp / sel_an[..., 1], 1e-9)),
                      0.0))
        tscale = tscale.at[bidx, la_c, gj, gi].set(
            jnp.where(ok, scale, 0.0))
        onehot = jax.nn.one_hot(glabel, class_num)
        if use_label_smooth:
            delta = 1.0 / max(class_num, 1)
            onehot = onehot * (1 - delta) + delta / class_num
        tcls = tcls.at[bidx, la_c, gj, gi].set(
            onehot * jnp.where(ok, 1.0, 0.0)[..., None])
        # predictions
        px = jax.nn.sigmoid(feats[:, :, 0])
        py = jax.nn.sigmoid(feats[:, :, 1])
        pw = feats[:, :, 2]
        ph = feats[:, :, 3]
        pobj = feats[:, :, 4]
        pcls = feats[:, :, 5:].transpose(0, 1, 3, 4, 2)

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target + \
                jnp.log1p(jnp.exp(-jnp.abs(logit)))

        def bce_p(prob, target):
            p = jnp.clip(prob, 1e-7, 1 - 1e-7)
            return -(target * jnp.log(p) + (1 - target) * jnp.log(1 - p))

        loss_xy = (tscale * (bce_p(px, tx) + bce_p(py, ty)) * mask_pos
                   ).sum(axis=(1, 2, 3))
        loss_wh = (tscale * (jnp.abs(pw - tw) + jnp.abs(ph - th)) * mask_pos
                   ).sum(axis=(1, 2, 3))
        # ignore mask: predicted boxes overlapping any gt above thresh
        bx = (px + jnp.arange(W)[None, None, None, :]) / W
        by = (py + jnp.arange(H)[None, None, :, None]) / H
        bw = jnp.exp(pw) * an[None, :, 0, None, None] / in_w
        bh = jnp.exp(ph) * an[None, :, 1, None, None] / in_h
        px1, py1 = bx - bw / 2, by - bh / 2
        px2, py2 = bx + bw / 2, by + bh / 2
        gx1, gy1 = gx - gw / 2, gy - gh / 2
        gx2, gy2 = gx + gw / 2, gy + gh / 2
        ix1 = jnp.maximum(px1[:, :, :, :, None], gx1[:, None, None, None, :])
        iy1 = jnp.maximum(py1[:, :, :, :, None], gy1[:, None, None, None, :])
        ix2 = jnp.minimum(px2[:, :, :, :, None], gx2[:, None, None, None, :])
        iy2 = jnp.minimum(py2[:, :, :, :, None], gy2[:, None, None, None, :])
        iw = jnp.clip(ix2 - ix1, 0)
        ih = jnp.clip(iy2 - iy1, 0)
        inter2 = iw * ih
        areap = bw * bh
        areag = (gw * gh)[:, None, None, None, :]
        iou = inter2 / jnp.maximum(areap[..., None] + areag - inter2, 1e-9)
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        best_iou = iou.max(axis=-1)
        noobj_mask = ((best_iou < ignore_thresh) & (mask_pos < 0.5)
                      ).astype(jnp.float32)
        loss_obj = (bce(pobj, mask_pos) * (mask_pos + noobj_mask)
                    ).sum(axis=(1, 2, 3))
        loss_cls = (bce(pcls, tcls) * mask_pos[..., None]
                    ).sum(axis=(1, 2, 3, 4))
        return loss_xy + loss_wh + loss_obj + loss_cls

    ins = [x, gt_box, gt_label]
    if gt_score is not None:
        ins.append(gt_score)
    return apply("yolo_loss", f, ins)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Reference: vision/ops.py matrix_nms (SOLOv2 decay NMS) — host op
    (ragged output), single image or batch [N, M, 4] + [N, C, M]."""
    import numpy as onp
    b = onp.asarray(bboxes._data if isinstance(bboxes, Tensor) else bboxes)
    s = onp.asarray(scores._data if isinstance(scores, Tensor) else scores)
    outs, idxs, nums = [], [], []
    for n in range(b.shape[0]):
        dets = []
        det_idx = []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[n, c]
            keep = onp.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[onp.argsort(-sc[keep])][:nms_top_k]
            boxes_c = b[n, order]
            sc_c = sc[order]
            # iou matrix (upper triangle)
            x1 = onp.maximum(boxes_c[:, None, 0], boxes_c[None, :, 0])
            y1 = onp.maximum(boxes_c[:, None, 1], boxes_c[None, :, 1])
            x2 = onp.minimum(boxes_c[:, None, 2], boxes_c[None, :, 2])
            y2 = onp.minimum(boxes_c[:, None, 3], boxes_c[None, :, 3])
            norm = 0.0 if normalized else 1.0
            iw = onp.clip(x2 - x1 + norm, 0, None)
            ih = onp.clip(y2 - y1 + norm, 0, None)
            inter = iw * ih
            area = (boxes_c[:, 2] - boxes_c[:, 0] + norm) * \
                (boxes_c[:, 3] - boxes_c[:, 1] + norm)
            iou = inter / onp.maximum(area[:, None] + area[None] - inter,
                                      1e-9)
            iou = onp.triu(iou, 1)
            max_iou = iou.max(axis=0)  # per column: worst overlap above
            if use_gaussian:
                decay = onp.exp(-(iou ** 2 - max_iou[None] ** 2)
                                / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) / onp.maximum(1 - max_iou[None], 1e-9)
                         ).min(axis=0)
            dec_sc = sc_c * decay
            ok = dec_sc >= post_threshold
            for i in onp.where(ok)[0]:
                dets.append([c, dec_sc[i], *boxes_c[i]])
                det_idx.append(n * b.shape[1] + order[i])
        dets = onp.asarray(dets, onp.float32).reshape(-1, 6)
        det_idx = onp.asarray(det_idx, onp.int64)
        if dets.shape[0] > keep_top_k:
            top = onp.argsort(-dets[:, 1])[:keep_top_k]
            dets, det_idx = dets[top], det_idx[top]
        outs.append(dets)
        idxs.append(det_idx)
        nums.append(dets.shape[0])
    out = Tensor(jnp.asarray(onp.concatenate(outs, 0)))
    ret = [out]
    if return_index:
        ret.append(Tensor(jnp.asarray(onp.concatenate(idxs, 0))))
    if return_rois_num:
        ret.append(Tensor(jnp.asarray(onp.asarray(nums, onp.int32))))
    return tuple(ret) if len(ret) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """Reference: vision/ops.py generate_proposals (RPN) — host op:
    decode anchors with deltas, clip, filter small, NMS per image."""
    import numpy as onp
    sc = onp.asarray(scores._data if isinstance(scores, Tensor) else scores)
    dl = onp.asarray(bbox_deltas._data
                     if isinstance(bbox_deltas, Tensor) else bbox_deltas)
    im = onp.asarray(img_size._data
                     if isinstance(img_size, Tensor) else img_size)
    an = onp.asarray(anchors._data
                     if isinstance(anchors, Tensor) else anchors
                     ).reshape(-1, 4)
    va = onp.asarray(variances._data
                     if isinstance(variances, Tensor) else variances
                     ).reshape(-1, 4)
    N, A, H, W = sc.shape
    offset = 1.0 if pixel_offset else 0.0
    rois_all, scores_all, nums = [], [], []
    for n in range(N):
        s_n = sc[n].transpose(1, 2, 0).reshape(-1)
        d_n = dl[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = onp.argsort(-s_n)[:pre_nms_top_n]
        s_o, d_o, a_o, v_o = s_n[order], d_n[order], an[order], va[order]
        aw = a_o[:, 2] - a_o[:, 0] + offset
        ah = a_o[:, 3] - a_o[:, 1] + offset
        acx = a_o[:, 0] + aw / 2
        acy = a_o[:, 1] + ah / 2
        cx = v_o[:, 0] * d_o[:, 0] * aw + acx
        cy = v_o[:, 1] * d_o[:, 1] * ah + acy
        w = onp.exp(onp.minimum(v_o[:, 2] * d_o[:, 2], 10.0)) * aw
        h = onp.exp(onp.minimum(v_o[:, 3] * d_o[:, 3], 10.0)) * ah
        props = onp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2 - offset, cy + h / 2 - offset], 1)
        ih, iw_ = im[n, 0], im[n, 1]
        props[:, 0] = props[:, 0].clip(0, iw_ - offset)
        props[:, 1] = props[:, 1].clip(0, ih - offset)
        props[:, 2] = props[:, 2].clip(0, iw_ - offset)
        props[:, 3] = props[:, 3].clip(0, ih - offset)
        ws = props[:, 2] - props[:, 0] + offset
        hs = props[:, 3] - props[:, 1] + offset
        keep = onp.where((ws >= min_size) & (hs >= min_size))[0]
        props, s_k = props[keep], s_o[keep]
        # greedy nms
        order2 = onp.argsort(-s_k)
        selected = []
        while order2.size and len(selected) < post_nms_top_n:
            i = order2[0]
            selected.append(i)
            if order2.size == 1:
                break
            rest = order2[1:]
            xx1 = onp.maximum(props[i, 0], props[rest, 0])
            yy1 = onp.maximum(props[i, 1], props[rest, 1])
            xx2 = onp.minimum(props[i, 2], props[rest, 2])
            yy2 = onp.minimum(props[i, 3], props[rest, 3])
            iw2 = onp.clip(xx2 - xx1 + offset, 0, None)
            ih2 = onp.clip(yy2 - yy1 + offset, 0, None)
            inter = iw2 * ih2
            a_i = (props[i, 2] - props[i, 0] + offset) * \
                (props[i, 3] - props[i, 1] + offset)
            a_r = (props[rest, 2] - props[rest, 0] + offset) * \
                (props[rest, 3] - props[rest, 1] + offset)
            iou = inter / onp.maximum(a_i + a_r - inter, 1e-9)
            order2 = rest[iou <= nms_thresh]
        rois_all.append(props[selected])
        scores_all.append(s_k[selected])
        nums.append(len(selected))
    rois = Tensor(jnp.asarray(onp.concatenate(rois_all, 0)
                              .astype(onp.float32)))
    rscores = Tensor(jnp.asarray(onp.concatenate(scores_all, 0)
                                 .astype(onp.float32)))
    if return_rois_num:
        return rois, rscores, Tensor(jnp.asarray(onp.asarray(nums,
                                                             onp.int32)))
    return rois, rscores


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Reference: vision/ops.py distribute_fpn_proposals: route each RoI to
    its FPN level by sqrt(area) relative to refer_scale."""
    import numpy as onp
    rois = onp.asarray(fpn_rois._data
                       if isinstance(fpn_rois, Tensor) else fpn_rois)
    offset = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + offset
    h = rois[:, 3] - rois[:, 1] + offset
    scale = onp.sqrt(onp.clip(w * h, 0, None))
    lvl = onp.floor(onp.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = onp.clip(lvl, min_level, max_level).astype(onp.int64)
    # per-image ownership of each roi (reference returns per-level
    # rois_num of shape [N] so callers can split every level per image)
    if rois_num is not None:
        per_img = onp.asarray(rois_num._data if isinstance(
            rois_num, Tensor) else rois_num, onp.int64)
        img_of = onp.repeat(onp.arange(per_img.size), per_img)
    else:
        per_img = None
        img_of = onp.zeros(rois.shape[0], onp.int64)
    multi_rois = []
    restore = onp.zeros(rois.shape[0], onp.int64)
    rois_num_per = []
    order = []
    for l in range(min_level, max_level + 1):
        idx = onp.where(lvl == l)[0]
        # within a level, keep image-major order (reference layout)
        idx = idx[onp.argsort(img_of[idx], kind="stable")]
        multi_rois.append(Tensor(jnp.asarray(
            rois[idx].astype(onp.float32).reshape(-1, 4))))
        if per_img is not None:
            counts = onp.bincount(img_of[idx],
                                  minlength=per_img.size)
            rois_num_per.append(Tensor(jnp.asarray(
                counts.astype(onp.int32))))
        else:
            rois_num_per.append(Tensor(jnp.asarray(
                onp.asarray([idx.size], onp.int32))))
        order.extend(idx.tolist())
    restore[onp.asarray(order, onp.int64)] = onp.arange(len(order))
    restore_t = Tensor(jnp.asarray(restore.reshape(-1, 1)))
    if rois_num is not None:
        return multi_rois, restore_t, rois_num_per
    return multi_rois, restore_t


def read_file(path, name=None):
    """Reference: vision/ops.py read_file — file bytes as a uint8 tensor."""
    with open(path, "rb") as f:
        data = f.read()
    import numpy as onp
    return Tensor(jnp.asarray(onp.frombuffer(data, onp.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Reference: vision/ops.py decode_jpeg (host decode; the reference
    uses nvjpeg on GPU, libjpeg on CPU). Returns [C, H, W] uint8."""
    import io

    import numpy as onp
    from PIL import Image
    data = onp.asarray(x._data if isinstance(x, Tensor) else x,
                       onp.uint8).tobytes()
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = onp.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


class RoIAlign(object):
    """Reference: vision/ops.py RoIAlign layer."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._args = (output_size, spatial_scale)

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._args[0],
                         spatial_scale=self._args[1])


class RoIPool(object):
    def __init__(self, output_size, spatial_scale=1.0):
        self._args = (output_size, spatial_scale)

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._args[0],
                        spatial_scale=self._args[1])


class PSRoIPool(object):
    def __init__(self, output_size, spatial_scale=1.0):
        self._args = (output_size, spatial_scale)

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._args[0],
                          spatial_scale=self._args[1])


class DeformConv2D(object):
    """Reference: vision/ops.py DeformConv2D layer (DCNv1/v2)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        import paddle_tpu as _paddle
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        fan = in_channels * ks[0] * ks[1]
        bound = 1.0 / np.sqrt(fan)
        rng = np.random.RandomState(0)
        self.weight = _paddle.to_tensor(rng.uniform(
            -bound, bound, (out_channels, in_channels // groups,
                            ks[0], ks[1])).astype("float32"))
        self.weight.stop_gradient = False
        self.bias = None
        if bias_attr is not False:
            self.bias = _paddle.to_tensor(
                np.zeros((out_channels,), "float32"))
            self.bias.stop_gradient = False
        self._cfg = (stride, padding, dilation, deformable_groups, groups)

    def __call__(self, x, offset, mask=None):
        s, p, d, dg, g = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias, stride=s,
                             padding=p, dilation=d, deformable_groups=dg,
                             groups=g, mask=mask)


__all__ += ["roi_pool", "psroi_pool", "deform_conv2d", "box_coder",
            "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
            "generate_proposals", "distribute_fpn_proposals", "read_file",
            "decode_jpeg", "RoIAlign", "RoIPool", "PSRoIPool",
            "DeformConv2D"]
