"""paddle_tpu.vision.models (reference: python/paddle/vision/models)."""
from ..models.lenet import LeNet  # noqa: F401
from ..models.resnet import (  # noqa: F401
    BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34, resnet50,
)
from ..models.vision_zoo import (  # noqa: F401
    AlexNet, MobileNetV1, MobileNetV2, VGG, alexnet, vgg11, vgg13, vgg16,
    vgg19,
)
