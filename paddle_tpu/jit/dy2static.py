"""Dy2static AST conversion: python if/while/for over Tensors → cond/while.

Reference: python/paddle/jit/dy2static/program_translator.py:773
(ASTStaticFunction → DygraphToStaticAst), ifelse_transformer.py (branch
functions over the assigned-name union), loop_transformer.py (loop-var
analysis → convert_while_loop), convert_operators.py (runtime dispatch on
Variable-ness of the predicate).

TPU-native: the rewrite targets runtime converters that check whether the
predicate holds a jax tracer — python control flow stays python when
concrete (zero overhead, exact reference semantics), and lowers onto
lax.cond / lax.while_loop (jit/control_flow.py) when tensor-dependent
under tracing. The reference's SOT bytecode path (jit/sot/translate.py:31)
is collapsed by the same mechanism: tracing IS the fallback-free fast
path, so only control flow needs conversion.

Supported shapes (reference basic dygraph_to_static coverage):
  - ``if <tensor-expr>:`` / ``elif`` / ``else`` — assigned-name union
    becomes the branch outputs; names must be bound on every path that
    reaches a later read (checked at runtime by the converter).
  - ``while <tensor-expr>:`` — loop vars = names assigned in the body and
    live afterwards (read in the condition or body before assignment).
  - ``for i in range(<tensor>)`` — rewritten to the while form.
  - ``break``/``continue``/``return`` inside converted blocks are NOT
    supported (reference break_continue_transformer.py) — a clear error
    asks for manual restructuring.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax
import numpy as np

from ..core.tensor import Tensor
from .control_flow import cond as _cond
from .control_flow import while_loop as _while_loop

__all__ = ["convert_to_static", "convert_ifelse", "convert_while",
           "unsupported_in_converted_block", "GraphBreak"]

_MISSING = object()  # name unbound on a branch/loop path


class GraphBreak(NotImplementedError):
    """Raised when tracing reaches a construct the static path cannot
    stage (return/break/continue under a traced predicate, data-dependent
    python). StaticFunction catches it and re-runs the region eagerly —
    the reference's SOT graph-break fallback (jit/sot/translate.py:31),
    where unsupported bytecode splits the graph instead of failing."""


def _is_traced_bool(x):
    return isinstance(x, Tensor) and isinstance(x._data, jax.core.Tracer)


def convert_ifelse(pred, true_fn, false_fn):
    """Runtime dispatch for a rewritten ``if`` (reference:
    convert_operators.py convert_ifelse)."""
    if _is_traced_bool(pred):
        return _cond(pred, true_fn, false_fn)
    taken = bool(np.asarray(pred._data)) if isinstance(pred, Tensor) \
        else bool(pred)
    return true_fn() if taken else false_fn()


def convert_while(cond_fn, body_fn, loop_vars):
    """Runtime dispatch for a rewritten ``while`` (reference:
    convert_operators.py convert_while_loop)."""
    probe = cond_fn(*loop_vars)
    if _is_traced_bool(probe) or any(
            isinstance(v, Tensor) and isinstance(v._data, jax.core.Tracer)
            for v in loop_vars):
        # loop-carried python numerics must become tensors: a traced
        # while's carry cannot change python values across iterations
        import jax.numpy as jnp
        promoted = []
        for v in loop_vars:
            if isinstance(v, bool) or v is _MISSING:
                promoted.append(v)
            elif isinstance(v, int):
                promoted.append(Tensor(jnp.asarray(v, jnp.int32)))
            elif isinstance(v, float):
                promoted.append(Tensor(jnp.asarray(v, jnp.float32)))
            else:
                promoted.append(v)
        return _while_loop(cond_fn, body_fn, promoted)
    vars_now = list(loop_vars)
    while bool(np.asarray(probe._data)) if isinstance(probe, Tensor) \
            else bool(probe):
        vars_now = list(body_fn(*vars_now))
        probe = cond_fn(*vars_now)
    return vars_now


def unsupported_in_converted_block(kind):
    raise GraphBreak(
        f"'{kind}' inside a tensor-dependent if/while cannot be staged by "
        "the dy2static converter (reference break_continue_transformer "
        "capability); to_static falls back to eager for this call "
        "(full_graph=True turns this into a hard error)")


def assert_concrete_pred(pred, kind):
    """Guard on an UNconverted block (it contains return/break/continue):
    fine as plain python while the predicate is concrete; a traced
    predicate would silently trace one branch, so fail loudly instead."""
    if _is_traced_bool(pred):
        unsupported_in_converted_block(kind)
    return pred


class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list (reference: ifelse_transformer's
    get_name_ids)."""

    def __init__(self):
        self.names: set = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)

    # nested defs bind their own scope
    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


class _ReadNames(ast.NodeVisitor):
    def __init__(self):
        self.names: set = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _read(nodes):
    v = _ReadNames()
    for n in nodes if isinstance(nodes, list) else [nodes]:
        v.visit(n)
    return v.names


def _contains_flow_escape(stmts):
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, (ast.Return, ast.Break, ast.Continue)):
                return type(node).__name__.lower()
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                break  # nested scopes own their control flow
    return None


def _name(id_, ctx):
    return ast.Name(id=id_, ctx=ctx)


def _maybe_tensor_pred(test):
    """Heuristic from the reference's IfElseTransformer: rewrite every if
    whose predicate isn't a literal/bool-op-of-literals; the runtime
    converter keeps python semantics for concrete predicates."""
    return not isinstance(test, (ast.Constant,))


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If/While/For(range) into convert_ifelse/convert_while
    calls. Only top-level function control flow is rewritten (nested defs
    and comprehensions keep python semantics)."""

    def __init__(self):
        self.counter = 0

    def _fresh(self, base):
        self.counter += 1
        return f"__pd_{base}_{self.counter}"

    def visit_FunctionDef(self, node):
        new_body = []
        for s in node.body:
            r = self.visit(s)
            if isinstance(r, list):
                new_body.extend(r)
            elif r is not None:
                new_body.append(r)
        node.body = new_body
        return node

    def _guard(self, node, esc):
        """Leave the block as plain python but wrap its test so a traced
        predicate fails loudly instead of tracing one branch."""
        node.test = ast.Call(
            func=_name("__pd_assert_concrete", ast.Load()),
            args=[node.test, ast.Constant(value=esc)], keywords=[])
        return node

    def _guard_unbound(self, names):
        """Names assigned inside a converted block may be unbound before
        it (reference: UndefinedVar) — bind them to the MISSING sentinel
        so the generated functions can reference them."""
        stmts = []
        for n in names:
            src = (f"try:\n    {n}\nexcept (NameError, "
                   f"UnboundLocalError):\n    {n} = __pd_MISSING")
            stmts.append(ast.parse(src).body[0])
        return stmts

    def _branch_fn(self, fn_name, body, out_names):
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(n, ast.Load()) for n in out_names],
            ctx=ast.Load()))
        return ast.FunctionDef(
            name=fn_name, args=ast.arguments(
                posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[],
                defaults=[]),
            body=list(body) + [ret], decorator_list=[],
            type_params=[])

    def visit_If(self, node):
        node = self.generic_visit(node)
        if not _maybe_tensor_pred(node.test):
            return node
        esc = _contains_flow_escape(node.body) or \
            _contains_flow_escape(node.orelse)
        if esc:
            return self._guard(node, esc)
        node.test = _LogicalTransformer().visit(node.test)
        out_names = sorted(
            n for n in (_assigned(node.body) | _assigned(node.orelse))
            if n != "_" and not n.startswith("__pd_"))
        true_name = self._fresh("true")
        false_name = self._fresh("false")
        stmts = self._guard_unbound(out_names) + [
            self._branch_fn(true_name, node.body, out_names),
            self._branch_fn(false_name, node.orelse or [ast.Pass()],
                            out_names),
        ]
        call = ast.Call(
            func=_name("__pd_convert_ifelse", ast.Load()),
            args=[node.test, _name(true_name, ast.Load()),
                  _name(false_name, ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n)
                                  for n in out_names], ctx=ast.Load())],
            keywords=[])
        if out_names:
            target = ast.Tuple(
                elts=[_name(n, ast.Store()) for n in out_names],
                ctx=ast.Store())
            stmts.append(ast.Assign(targets=[target], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        return stmts

    def _rewrite_loop(self, node, cond_expr, pre_stmts, body):
        loop_names = sorted(n for n in _assigned(body)
                            if n != "_" and not n.startswith("__pd_"))
        # names read by the condition ride along too (loop-invariant
        # tensors needed inside the traced cond_fn)
        cond_reads = sorted(n for n in _read(cond_expr) - set(loop_names)
                            if not n.startswith("__pd_"))
        all_names = loop_names + cond_reads
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in all_names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(n, ast.Load()) for n in all_names], ctx=ast.Load()))
        cond_name = self._fresh("loopcond")
        body_name = self._fresh("loopbody")
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=cond_expr)], decorator_list=[],
            type_params=[])
        body_fn = ast.FunctionDef(
            name=body_name, args=args, body=list(body) + [ret],
            decorator_list=[], type_params=[])
        call = ast.Call(
            func=_name("__pd_convert_while", ast.Load()),
            args=[_name(cond_name, ast.Load()),
                  _name(body_name, ast.Load()),
                  ast.List(elts=[_name(n, ast.Load()) for n in all_names],
                           ctx=ast.Load())],
            keywords=[])
        target = ast.List(
            elts=[_name(n, ast.Store()) for n in all_names],
            ctx=ast.Store())
        return pre_stmts + self._guard_unbound(loop_names) + \
            [cond_fn, body_fn, ast.Assign(targets=[target], value=call)]

    def visit_While(self, node):
        node = self.generic_visit(node)
        if node.orelse:
            return node  # while/else stays python
        if not _maybe_tensor_pred(node.test):
            return node
        esc = _contains_flow_escape(node.body)
        if esc:
            return self._guard(node, esc)
        return self._rewrite_loop(node, _LogicalTransformer().visit(
            node.test), [], node.body)

    def visit_For(self, node):
        node = self.generic_visit(node)
        # only `for <name> in range(<expr>)` is rewritten
        if (node.orelse or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or len(node.iter.args) not in (1, 2)
                or _contains_flow_escape(node.body)):
            return node
        ivar = node.target.id
        if len(node.iter.args) == 1:
            start = ast.Constant(value=0)
            stop = node.iter.args[0]
        else:
            start, stop = node.iter.args
        stop_name = self._fresh("stop")
        pre = [
            ast.Assign(targets=[_name(ivar, ast.Store())],
                       value=ast.Call(
                           func=_name("__pd_loop_index", ast.Load()),
                           args=[start], keywords=[])),
            ast.Assign(targets=[_name(stop_name, ast.Store())], value=stop),
        ]
        test = ast.Compare(left=_name(ivar, ast.Load()), ops=[ast.Lt()],
                           comparators=[_name(stop_name, ast.Load())])
        inc = ast.Assign(
            targets=[_name(ivar, ast.Store())],
            value=ast.BinOp(left=_name(ivar, ast.Load()), op=ast.Add(),
                            right=ast.Call(
                                func=_name("__pd_loop_index", ast.Load()),
                                args=[ast.Constant(value=1)], keywords=[])))
        return self._rewrite_loop(node, test, pre, list(node.body) + [inc])


def _convert_ifelse_rt(pred, true_fn, false_fn, names):
    outs = convert_ifelse(pred, true_fn, false_fn)
    return outs


def _loop_index(v):
    """Loop counters: keep python ints python (zero-overhead concrete
    loops); Tensors pass through for traced bounds."""
    return v


def convert_logical_and(l_fn, r_fn):
    """Reference: convert_operators.py convert_logical_and — lazy operands
    preserve python short-circuit for concrete values; tensors combine via
    logical_and."""
    lhs = l_fn()
    if isinstance(lhs, Tensor):
        return lhs.logical_and(_as_bool_tensor(r_fn()))
    if not lhs:
        return lhs
    rhs = r_fn()
    if isinstance(rhs, Tensor):
        return rhs
    return rhs


def convert_logical_or(l_fn, r_fn):
    lhs = l_fn()
    if isinstance(lhs, Tensor):
        return lhs.logical_or(_as_bool_tensor(r_fn()))
    if lhs:
        return lhs
    rhs = r_fn()
    return rhs


def convert_logical_not(v):
    if isinstance(v, Tensor):
        return v.logical_not()
    return not v


def _as_bool_tensor(v):
    if isinstance(v, Tensor):
        return v
    import jax.numpy as jnp
    return Tensor(jnp.asarray(bool(v)))


class _LogicalTransformer(ast.NodeTransformer):
    """and/or/not inside converted tests → lazy logical converters
    (reference: dy2static/transformers/logical_transformer.py)."""

    def visit_BoolOp(self, node):
        node = self.generic_visit(node)
        fn = "__pd_logical_and" if isinstance(node.op, ast.And) \
            else "__pd_logical_or"
        out = node.values[0]
        for rhs in node.values[1:]:
            out = ast.Call(
                func=_name(fn, ast.Load()),
                args=[ast.Lambda(args=ast.arguments(
                          posonlyargs=[], args=[], kwonlyargs=[],
                          kw_defaults=[], defaults=[]), body=out),
                      ast.Lambda(args=ast.arguments(
                          posonlyargs=[], args=[], kwonlyargs=[],
                          kw_defaults=[], defaults=[]), body=rhs)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node):
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_name("__pd_logical_not", ast.Load()),
                            args=[node.operand], keywords=[])
        return node


def convert_to_static(fn):
    """AST-rewrite ``fn`` so tensor-dependent if/while/for(range) lower to
    cond/while_loop under tracing. Returns ``fn`` unchanged when source is
    unavailable (builtins, lambdas from REPL, C extensions)."""
    if getattr(fn, "_not_to_static", False) or \
            getattr(fn, "__pd_converted__", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # run undecorated (to_static re-wraps)
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    glb = dict(fn.__globals__)
    glb["__pd_convert_ifelse"] = _convert_ifelse_rt
    glb["__pd_convert_while"] = convert_while
    glb["__pd_unsupported"] = unsupported_in_converted_block
    glb["__pd_assert_concrete"] = assert_concrete_pred
    glb["__pd_loop_index"] = _loop_index
    glb["__pd_logical_and"] = convert_logical_and
    glb["__pd_logical_or"] = convert_logical_or
    glb["__pd_logical_not"] = convert_logical_not
    glb["__pd_MISSING"] = _MISSING
    # closures: rebuild the cell environment as globals (the rewritten
    # function is exec'd at module scope, reference precedent:
    # dy2static/utils.py func_to_source_code + ast_to_func)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass  # unfilled cell (self-reference); leave unbound
    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    exec(code, glb)
    converted = glb[fdef.name]
    converted = functools.wraps(fn)(converted)
    converted.__pd_converted__ = True
    return converted
