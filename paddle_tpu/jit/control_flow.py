"""Tensor-dependent control flow: paddle.static.nn.cond / while_loop.

Reference: python/paddle/static/nn/control_flow.py (cond:1153,
while_loop:1384 build ConditionalBlock/While ops into the Program;
jit/dy2static/convert_operators.py routes python if/while here when the
predicate is a Variable). TPU-native collapse: under to_static tracing a
Tensor holds a jax tracer, so tensor-dependent branching lowers directly
onto XLA's native control flow — ``lax.cond`` / ``lax.while_loop`` —
inside the same traced program; with a concrete predicate both are plain
python (eager semantics, taped as usual).

Autograd through ``cond``: the branch closures are discovered by running
each branch once under a read-recorder (dispatch hook) to find every
*external differentiable* Tensor they touch; those become explicit inputs
of one taped ``apply`` whose array function is ``lax.cond``, so the tape's
``jax.vjp`` differentiates the selected branch exactly like the
reference's conditional_block_grad. Non-differentiable captures stay
closure-captured (jax threads closed-over tracers automatically).

``while_loop`` is forward-only under tracing (XLA's while has no
reverse-mode); training loops with a static trip count should use
``paddle.static.nn.scan_loop`` (bounded, differentiable, lax.scan).
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core import dispatch as _dispatch
from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "scan_loop", "case", "switch_case"]


class _ReadRecorder:
    """Collects external differentiable Tensors read by a branch: an input
    whose id was never produced by an op inside the recorded region."""

    def __init__(self):
        self.created: set = set()
        self.reads: dict = {}

    def note(self, inputs, results):
        for t in inputs:
            if isinstance(t, Tensor) and id(t) not in self.created \
                    and _dispatch._is_diff(t):
                self.reads.setdefault(id(t), t)
        res = results if isinstance(results, (tuple, list)) else (results,)
        for r in res:
            if isinstance(r, Tensor):
                self.created.add(id(r))


@contextlib.contextmanager
def _recording(rec):
    prev = _dispatch._cf_recorder
    _dispatch._cf_recorder = rec
    try:
        yield
    finally:
        _dispatch._cf_recorder = prev


@contextlib.contextmanager
def _patched_reads(reads, read_arrs):
    """Temporarily point externally-captured Tensors at the traced arrays
    that represent them inside a lax region (shared by cond, scan_loop and
    the differentiable while path)."""
    saved = [(t, t._data) for t in reads]
    try:
        for t, a in zip(reads, read_arrs):
            t._data = a
        yield
    finally:
        for t, a in saved:
            t._data = a


def _check_same_state(skel, tensors, out, what):
    """Body outputs must match the loop-var structure/shapes/dtypes."""
    out_list = list(out) if isinstance(out, (tuple, list)) else [out]
    new_tensors, new_skel = _flatten(out_list)
    if _skel_sig(new_skel, new_tensors) != _skel_sig(skel, tensors):
        raise ValueError(
            f"{what} must return the same structure/shapes as loop_vars: "
            f"{_skel_sig(skel, tensors)} vs {_skel_sig(new_skel, new_tensors)}")
    return new_tensors


def _flatten(out):
    """Flatten a branch output pytree into (tensors, skeleton)."""
    from .api import _tree_flatten
    tensors: list = []
    skel = _tree_flatten(out, tensors, [])
    return tensors, skel


def _rebuild(skel, arrays, wrap=None):
    from .api import _tree_rebuild
    return _tree_rebuild(skel, list(arrays),
                         wrap or (lambda a: Tensor(a)))


def _skel_sig(skel, tensors):
    return (repr(skel), tuple((tuple(t.shape), str(t.dtype))
                              for t in tensors))


def _is_traced(*vals):
    for v in vals:
        arr = v._data if isinstance(v, Tensor) else v
        if isinstance(arr, jax.core.Tracer):
            return True
    return False


def _scalar_pred(arr):
    return jnp.reshape(arr.astype(jnp.bool_), ())


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Reference: paddle.static.nn.cond (static/nn/control_flow.py:1153).

    Branches take no arguments and may close over any in-scope Tensor;
    both must return the same structure (shapes/dtypes must match, as in
    the reference and as XLA requires).
    """
    pred_arr = pred._data if isinstance(pred, Tensor) else jnp.asarray(pred)
    if not _is_traced(pred):
        taken = bool(np.asarray(pred_arr))
        if taken:
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None

    if true_fn is None or false_fn is None:
        raise ValueError(
            "cond over a traced predicate requires both true_fn and "
            "false_fn (XLA compiles both branches)")

    # discovery pass: find external diff reads + output structure. Branch
    # functions must be effect-free (mutating state other than their
    # return value is undefined, matching lax.cond semantics).
    rec = _ReadRecorder()
    with _recording(rec), autograd.no_grad():
        out_t = true_fn()
        out_f = false_fn()
    t_tensors, t_skel = _flatten(out_t)
    f_tensors, f_skel = _flatten(out_f)
    if _skel_sig(t_skel, t_tensors) != _skel_sig(f_skel, f_tensors):
        raise ValueError(
            "cond branches must return the same structure/shapes/dtypes: "
            f"true_fn -> {_skel_sig(t_skel, t_tensors)}, "
            f"false_fn -> {_skel_sig(f_skel, f_tensors)}")
    reads = list(rec.reads.values())
    n_out = len(t_tensors)
    if n_out == 0:
        raise ValueError(
            "cond over a traced predicate requires the branches to return "
            "at least one Tensor (side-effect-only branches cannot lower "
            "to lax.cond)")

    def fwd(pred_a, *read_arrs):
        def make(branch_fn):
            def run(read_vals):
                with _patched_reads(reads, read_vals):
                    with autograd.no_grad():
                        out = branch_fn()
                    tensors, _ = _flatten(out)
                    return tuple(x._data for x in tensors)
            return run

        res = jax.lax.cond(_scalar_pred(pred_a), make(true_fn),
                           make(false_fn), tuple(read_arrs))
        return res if n_out != 1 else res[0]

    ins = [Tensor(pred_arr, stop_gradient=True)] + reads
    out = apply("cond", fwd, ins, nout=n_out)
    out_tensors = list(out) if isinstance(out, tuple) else [out]
    # rebuild with the apply-returned Tensors so tape linkage survives
    return _rebuild(t_skel, out_tensors, wrap=lambda t: t)


def _loop_state(loop_vars):
    as_seq = isinstance(loop_vars, (list, tuple))
    vars_list = list(loop_vars) if as_seq else [loop_vars]
    tensors, skel = _flatten(vars_list)
    return vars_list, tensors, skel, as_seq


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None,
               max_steps=None):
    """Reference: paddle.static.nn.while_loop (control_flow.py:1384);
    backward capability: while_op.cc WhileGrad.

    cond_fn(*loop_vars) -> boolean Tensor; body_fn(*loop_vars) -> updated
    loop_vars (same structure/shapes). Eager it is a plain python loop
    (fully taped, so backward just works). Under tracing it lowers to
    lax.while_loop — forward-only — UNLESS ``max_steps`` is given, in
    which case the differentiable bounded-unroll-with-mask path runs: a
    ``lax.scan`` of ``max_steps`` iterations where finished iterations
    pass state through unchanged (``where(active, body(s), s)``). The
    result and its gradient equal the true loop's for any trip count
    <= max_steps — the TPU rebuild of WhileGrad (XLA's while has no
    reverse-mode, so the bound is what buys differentiability).

    Caveat (standard masked-unroll): body_fn keeps executing on the final
    state after the predicate goes false (results discarded); it must not
    produce NaN/Inf there, or the zeros-times-NaN in backward poisons
    gradients.
    """
    vars_list, tensors, skel, as_seq = _loop_state(loop_vars)

    pred0 = cond_fn(*vars_list)
    if not _is_traced(pred0, *tensors):
        steps = 0
        while bool(np.asarray(pred0._data if isinstance(pred0, Tensor)
                              else pred0)):
            if max_steps is not None and steps >= max_steps:
                break  # same hard bound as the traced masked-unroll path
            out = body_fn(*vars_list)
            vars_list, tensors, new_skel, _ = _loop_state(
                out if isinstance(out, (list, tuple)) else [out])
            pred0 = cond_fn(*vars_list)
            steps += 1
        return vars_list if as_seq else vars_list[0]

    if max_steps is not None and autograd.is_grad_enabled():
        # the traced-but-stop-gradient case (to_static lifts args as
        # stop_gradient tensors and differentiates the whole program
        # functionally) cannot be told apart from genuinely non-diff use,
        # so with a bound given and grad on, take the differentiable
        # path — identical forward semantics for any trip count <=
        # max_steps. Under no_grad the early-exiting lax.while_loop below
        # is strictly cheaper (inference decode loops).
        return _while_loop_grad(cond_fn, body_fn, vars_list, tensors, skel,
                                as_seq, int(max_steps))

    if autograd.is_grad_enabled() and any(_dispatch._is_diff(t)
                                          for t in tensors):
        raise RuntimeError(
            "while_loop over traced tensors is forward-only (XLA's "
            "while has no reverse-mode autodiff). Pass max_steps=N for "
            "the differentiable masked-unroll path, wrap in "
            "paddle.no_grad(), mark loop vars stop_gradient, or use "
            "paddle.static.nn.scan_loop (bounded, differentiable).")

    def run(flat):
        def c(carry):
            step, flat_vals = carry
            vs = _rebuild(skel, flat_vals)
            with autograd.no_grad():
                p = cond_fn(*vs)
            p_arr = _scalar_pred(p._data if isinstance(p, Tensor) else p)
            if max_steps is not None:  # same hard bound as the other modes
                p_arr = jnp.logical_and(p_arr, step < max_steps)
            return p_arr

        def b(carry):
            step, flat_vals = carry
            vs = _rebuild(skel, flat_vals)
            with autograd.no_grad():
                out = body_fn(*vs)
            new_tensors = _check_same_state(skel, tensors, out,
                                            "while_loop body")
            return step + 1, tuple(t._data for t in new_tensors)

        _, final = jax.lax.while_loop(
            c, b, (jnp.asarray(0, jnp.int32), tuple(flat)))
        return final

    res = run([t._data for t in tensors])
    out_vars = _rebuild(skel, res,
                        wrap=lambda a: Tensor(a, stop_gradient=True))
    return out_vars if as_seq else out_vars[0]


def _while_loop_grad(cond_fn, body_fn, vars_list, tensors, skel, as_seq,
                     max_steps):
    """Differentiable while: scan of ``max_steps`` masked steps (reference
    WhileGrad capability, while_op.cc). Each step evaluates the predicate
    on the live state; once false, subsequent steps are identity, so the
    final carry equals the true loop result and jax.vjp through the scan
    yields exactly the loop's gradient (inactive steps contribute identity
    cotangent propagation)."""
    rec = _ReadRecorder()
    with _recording(rec), autograd.no_grad():
        probe = body_fn(*vars_list)
        cond_fn(*vars_list)
    _check_same_state(skel, tensors, probe, "while_loop body")
    reads = [t for t in rec.reads.values()
             if not any(t is v for v in tensors)]
    n_state = len(tensors)

    def fwd(*arrs):
        state0 = tuple(arrs[:n_state])
        read_arrs = arrs[n_state:]

        def run_region(fn, vs_flat):
            with _patched_reads(reads, read_arrs):
                vs = _rebuild(skel, vs_flat)
                with autograd.no_grad():
                    return fn(*vs)

        def step(carry, _):
            done, state = carry
            p = run_region(cond_fn, state)
            p_arr = _scalar_pred(p._data if isinstance(p, Tensor) else p)
            new_done = jnp.logical_or(done, jnp.logical_not(p_arr))
            active = jnp.logical_not(new_done)
            out = run_region(body_fn, state)
            out_list = list(out) if isinstance(out, (list, tuple)) else [out]
            new_tensors, _ = _flatten(out_list)
            new_state = tuple(
                jnp.where(active, n._data, o)
                for n, o in zip(new_tensors, state))
            return (new_done, new_state), None

        (done, final), _ = jax.lax.scan(
            step, (jnp.asarray(False), state0), None, length=max_steps)
        return final if n_state != 1 else final[0]

    out = apply("while_loop_grad", fwd, tensors + reads, nout=n_state)
    out_tensors = list(out) if isinstance(out, tuple) else [out]
    out_vars = _rebuild(skel, out_tensors, wrap=lambda t: t)
    return out_vars if as_seq else out_vars[0]


def scan_loop(body_fn, loop_vars, n_steps, name=None):
    """Bounded differentiable loop (no reference analog; the TPU answer to
    backward-through-while): runs body_fn exactly n_steps times via
    lax.scan through one taped apply, so gradients flow (reference
    while_grad capability for static-trip-count loops).

    body_fn(step, *loop_vars) -> updated loop_vars.
    """
    vars_list, tensors, skel, as_seq = _loop_state(loop_vars)
    if not isinstance(n_steps, int):
        raise TypeError("scan_loop needs a static python int n_steps")

    rec = _ReadRecorder()
    with _recording(rec), autograd.no_grad():
        probe = body_fn(Tensor(jnp.asarray(0, jnp.int32)), *vars_list)
    _check_same_state(skel, tensors, probe, "scan_loop body")
    reads = [t for t in rec.reads.values()
             if not any(t is v for v in tensors)]
    n_state = len(tensors)

    def fwd(*arrs):
        state0 = tuple(arrs[:n_state])
        read_arrs = arrs[n_state:]

        def step(carry, i):
            with _patched_reads(reads, read_arrs):
                vs = _rebuild(skel, carry)
                with autograd.no_grad():
                    out = body_fn(Tensor(i), *vs)
                out_list = list(out) if isinstance(out, (list, tuple)) \
                    else [out]
                new_tensors, _ = _flatten(out_list)
                return tuple(t._data for t in new_tensors), None

        final, _ = jax.lax.scan(step, state0,
                                jnp.arange(n_steps, dtype=jnp.int32))
        return final if n_state != 1 else final[0]

    out = apply("scan_loop", fwd, tensors + reads, nout=n_state)
    out_tensors = list(out) if isinstance(out, tuple) else [out]
    out_vars = _rebuild(skel, out_tensors, wrap=lambda t: t)
    return out_vars if as_seq else out_vars[0]


def case(pred_fn_pairs, default=None, name=None):
    """Reference: paddle.static.nn.case — first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return cond(pred, fn, fn)
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference: paddle.static.nn.switch_case."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = list(enumerate(branch_fns))
    idx = branch_index if isinstance(branch_index, Tensor) else \
        Tensor(jnp.asarray(branch_index))
    pred_fn_pairs = [(idx.equal(Tensor(jnp.asarray(i, idx._data.dtype))), fn)
                     for i, fn in pairs]
    if default is None:
        default = pairs[-1][1]
    return case(pred_fn_pairs, default)
