"""jit.to_static — trace-to-XLA compilation.

Reference: python/paddle/jit/api.py:171 (to_static). The reference lowers
dygraph python to ProgramDesc/PIR via AST rewriting + SOT bytecode
interception, then executes with the static executor and optionally CINN.
TPU-native collapse (SURVEY §7 step 4): eager Tensors transparently hold jax
tracers, so to_static simply re-runs the python function under jax.jit —
parameters/buffers are lifted to traced inputs, the op tape records pullbacks
on tracers, and XLA compiles the whole graph (this one mechanism replaces
dy2static, SOT, PIR, CINN and the new executor).

Two modes:
- forward staging (default): the compiled function participates in the eager
  autograd tape (its pullback is the compiled VJP), so ``loss.backward()``
  outside still works — matching reference to_static training semantics.
- whole-step staging (``capture=(model, optimizer)``): the function may call
  ``backward()`` + ``optimizer.step()`` inside; parameters, buffers and
  optimizer accumulators become donated inputs/outputs of one XLA program —
  the max-performance train-step path (no reference analog; CINN whole-graph
  fusion is the closest).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..core.dispatch import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = ["to_static", "not_to_static", "InputSpec", "StaticFunction",
           "ignore_module"]


class _EagerFallback(Exception):
    """Internal: this input signature graph-broke before — skip tracing."""


def _aval(a):
    """Abstract value for compiled_text()/aot avals. Mesh shardings matter
    for SPMD lowering; single-device placements are left off (committed
    single-device avals would conflict with mesh-sharded peers at lower()
    time)."""
    sh = getattr(a, "sharding", None)
    if sh is not None and hasattr(sh, "mesh"):
        try:
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
        except Exception:
            pass
    return jax.ShapeDtypeStruct(a.shape, a.dtype)


_STRUCT_VERSION = None


def _struct_version():
    """The nn.Layer structural version counter (lazy: jit.api must not
    import nn at module load)."""
    global _STRUCT_VERSION
    if _STRUCT_VERSION is None:
        from ..nn.layer.layers import STRUCT_VERSION
        _STRUCT_VERSION = STRUCT_VERSION
    return _STRUCT_VERSION


_IDLE_SPEC = None


def _idle_rng_spec():
    """Shared RNG spec for steps whose trace consumed no randomness: the
    global generator is neither advanced nor touched, and no per-step
    host→device constant is created."""
    global _IDLE_SPEC
    if _IDLE_SPEC is None:
        _IDLE_SPEC = np.zeros(3, np.uint32)
    return _IDLE_SPEC


class _break_key_scope:
    """Tags exceptions escaping a trace/execute region with the cache key
    they broke under, so __call__ blacklists the right signature even when
    nested calls of the same StaticFunction are in flight."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        return self

    def __exit__(self, etype, e, tb):
        if e is not None and not isinstance(e, _EagerFallback) \
                and not hasattr(e, "_pd_break_key"):
            try:
                e._pd_break_key = self._key
            except AttributeError:
                pass
        return False


def _is_graph_break(e):
    """True when the exception means 'this python cannot be staged' (not
    'the user program is wrong'): our converter's explicit GraphBreak plus
    jax's trace-time concretization family (data-dependent bool/int/array
    use of a tracer, leaked tracers from side-effecty code). The dispatch
    layer wraps jax errors with op context (`raise ... from e`), so walk
    the cause chain. StaticFunction degrades to eager on these — the SOT
    graph-break analog."""
    from .dy2static import GraphBreak
    kinds = (GraphBreak, jax.errors.ConcretizationTypeError,
             jax.errors.TracerArrayConversionError,
             jax.errors.TracerIntegerConversionError,
             jax.errors.UnexpectedTracerError)
    seen = 0
    while e is not None and seen < 10:
        if isinstance(e, kinds):
            return True
        e = e.__cause__
        seen += 1
    return False


class InputSpec:
    """Reference: paddle.static.InputSpec — shape may contain None for
    dynamic dims (compiled polymorphically via jax.export symbolic shapes
    where supported; concrete shapes otherwise)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype) or jnp.float32
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _tree_flatten(obj, tensors, rebuild_path):
    """Flatten nested args: collect Tensors, return a skeleton rebuilder key."""
    if isinstance(obj, Tensor):
        tensors.append(obj)
        return ("T", len(tensors) - 1)
    if isinstance(obj, (list, tuple)):
        return (type(obj).__name__,
                tuple(_tree_flatten(o, tensors, rebuild_path) for o in obj))
    if isinstance(obj, dict):
        # flatten in sorted-key order so tensor indices are insertion-order
        # independent (two dicts with equal keys flatten identically)
        return ("dict", tuple(
            (k, _tree_flatten(obj[k], tensors, rebuild_path))
            for k in sorted(obj)))
    return ("C", obj)  # static constant (part of cache key)


def _tree_rebuild(skel, arrays, wrap):
    kind = skel[0]
    if kind == "T":
        return wrap(arrays[skel[1]])
    if kind in ("list", "tuple"):
        seq = [_tree_rebuild(s, arrays, wrap) for s in skel[1]]
        return seq if kind == "list" else tuple(seq)
    if kind == "dict":
        return {k: _tree_rebuild(v, arrays, wrap) for k, v in skel[1]}
    return skel[1]


def _amp_key():
    """AMP autocast decisions are baked in at trace time, so the compile
    cache must be keyed on the active auto_cast state (ADVICE r2) —
    including custom white/black op lists, which also steer amp_dtype_for.
    The frozenset construction is cached per state identity (auto_cast
    replaces, never mutates, the white/black sets) so the per-step key
    probe is a tuple build, not two set copies."""
    from ..amp.auto_cast import amp_key_cached
    return amp_key_cached()


def _static_key(skel, tensors, extra):
    shapes = tuple((tuple(t.shape), str(t.dtype)) for t in tensors)

    def hashable(s):
        kind = s[0]
        if kind == "C":
            try:
                hash(s[1])
                return s
            except TypeError:
                return ("C", repr(s[1]))
        if kind in ("list", "tuple", "dict"):
            return (kind, tuple(hashable(x) if not isinstance(x, str) else x
                                for x in s[1]))
        return s
    return (hashable(skel), shapes, extra)


def _convert_fn(fn):
    """Dy2static AST pass: tensor-dependent python if/while/for(range)
    lower onto lax control flow (reference: program_translator.py:773);
    bound methods are converted on __func__ and re-bound."""
    import inspect
    import types

    from .dy2static import convert_to_static
    if inspect.ismethod(fn):
        conv = convert_to_static(fn.__func__)
        if conv is not fn.__func__:
            return types.MethodType(conv, fn.__self__)
        return fn
    return convert_to_static(fn)


class StaticFunction:
    """Callable wrapper produced by to_static (reference:
    jit/dy2static/program_translator.py ASTStaticFunction analog)."""

    def __init__(self, function, input_spec=None, capture=None,
                 build_strategy=None, backend=None, full_graph=False,
                 donate_state=True, convert_control_flow=True):
        from ..nn import Layer
        self._raw_fn = function
        self._input_spec = input_spec
        self._capture = list(capture) if capture is not None else None
        self._donate_state = donate_state
        self._full_graph = full_graph
        self._broken_keys = set()  # input signatures that graph-broke
        self._cache = {}
        self._state_cache = None   # cached _state() walk (invalidate())
        self._fast_step = {}       # steady-state whole-step dispatch memo
        self._layer = None
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        else:
            self._fn = function
            owner = getattr(function, "__self__", None)
            if isinstance(owner, Layer):
                self._layer = owner
        if convert_control_flow:
            self._fn = _convert_fn(self._fn)

    # -- state discovery --
    def invalidate(self):
        """Drop the cached state walk + fast-dispatch memo. Call after a
        structural change to a captured module (adding/removing sublayers
        or parameters, re-materializing optimizer state) — the staged step
        otherwise keeps using the parameter set discovered at first call."""
        self._state_cache = None
        self._fast_step = {}

    def _state_cached(self):
        """The `_state()` walk (a full recursive parameters()/buffers()
        traversal of every captured layer) costs O(model size) python per
        call — caching it is a large slice of the per-step host-overhead
        win. The cache is guarded by the process-wide Layer structural
        version: any parameter/sublayer/buffer registration anywhere bumps
        it, forcing a re-walk (and, if the captured module really changed,
        a re-key + retrace) — :meth:`invalidate` remains for exotic edits
        the registration hooks cannot see (direct `_parameters` dict
        mutation)."""
        st = getattr(self, "_state_cache", None)
        if st is not None and _struct_version()[0] != self._state_version:
            # a Layer somewhere gained/lost a param/sublayer/buffer since
            # the walk — stale state must never reach the forward path OR
            # the whole-step slow path, not just the fast memo
            self.invalidate()
            st = None
        if st is None:
            st = self._state()
            self._state_cache = st
            self._state_version = _struct_version()[0]
        return st

    def _state(self):
        """(diff_params, buffers, opt_slots): every mutable tensor/array the
        traced function can read or write."""
        from ..nn import Layer
        from ..optimizer import Optimizer
        layers, opts = [], []
        if self._layer is not None:
            layers.append(self._layer)
        for item in self._capture or []:
            if isinstance(item, Layer):
                layers.append(item)
            elif isinstance(item, Optimizer):
                opts.append(item)
            elif isinstance(getattr(item, "_inner", None), Optimizer):
                opts.append(item._inner)  # sharding/hybrid wrappers
            elif isinstance(getattr(item, "_inner_opt", None), Optimizer):
                opts.append(item._inner_opt)
            else:
                raise TypeError(
                    f"capture item {type(item).__name__} is neither a Layer "
                    "nor an Optimizer (or optimizer wrapper); its state "
                    "cannot be staged")
        params, buffers = [], []
        seen = set()
        for layer in layers:
            for p in layer.parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    params.append(p)
            for b in layer.buffers():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    buffers.append(b)
        slots = []
        for opt in opts:
            slots.extend(opt._state_slots())
        # grad-sync schedulers (overlap engine) can carry cross-step device
        # state of their own: the quantized transports' per-bucket error-
        # feedback residuals. Any attached scheduler watching this step's
        # parameters exposes the same _state_slots protocol as an
        # optimizer — staging the residuals lets the quantized DP path
        # serve inside the compiled step instead of falling back to the
        # exact psum (ROADMAP item 2c).
        from ..core.autograd import _grad_sync_hooks
        pids = {id(p) for p in params}
        for ref in list(_grad_sync_hooks):
            hook = ref()
            if hook is None or not hasattr(hook, "_state_slots"):
                continue
            if pids & set(hook.param_ids()):
                slots.extend(hook._state_slots())
        return params, buffers, slots, layers, opts

    def __call__(self, *args, **kwargs):
        try:
            if self._capture is not None:
                from ..distributed import watchdog as _watchdog
                _watchdog.beat()  # collective-hang watchdog (if armed)
                return self._call_whole_step(args, kwargs)
            return self._call_forward(args, kwargs)
        except _EagerFallback:
            return self._eager_fallback(args, kwargs)
        except Exception as e:
            if self._full_graph or not _is_graph_break(e):
                raise
            import warnings
            first_line = (str(e).splitlines() or [""])[0]
            warnings.warn(
                f"to_static: falling back to eager for "
                f"{getattr(self._raw_fn, '__name__', self._raw_fn)} — "
                f"{type(e).__name__}: {first_line[:200]} "
                "(graph-break fallback; pass full_graph=True to make this "
                "an error)", stacklevel=2)
            # cache the break per input signature (SOT's guarded-subgraph
            # analog): this call pattern skips tracing from now on, while
            # other shapes/paths that staged fine keep their compiled
            # entry. The key rides on the exception (not instance state) so
            # nested calls of the same StaticFunction can't clobber it.
            key = getattr(e, "_pd_break_key", None)
            if key is not None:
                self._broken_keys.add(key)
                self._cache.pop(key, None)  # entry never executed compiled
            return self._eager_fallback(args, kwargs)

    def _eager_fallback(self, args, kwargs):
        """SOT-analog graph break (reference jit/sot/translate.py:31): the
        region that refused to stage runs eagerly. The converted fn keeps
        exact python semantics for concrete predicates, so correctness is
        unchanged — only staging is lost. Caveat (inherent to trace-then-
        rerun, unlike SOT's pre-execution bytecode split): the breaking
        call runs the function's python twice, so side effects before the
        break repeat; tracked params/buffers are restored by the trace's
        ``finally`` so tensor state is safe."""
        if self._capture is not None:
            from ..distributed import watchdog as _watchdog
            _watchdog.beat()
        return self._fn(*args, **kwargs)

    # -- mode 1: compiled forward on the eager tape --
    def _call_forward(self, args, kwargs):
        params, buffers, _, layers, _ = self._state_cached()
        arg_tensors: list = []
        skel = _tree_flatten((args, tuple(sorted(kwargs.items()))),
                             arg_tensors, [])
        training = tuple(layer.training for layer in layers)
        key_extra = ("fwd", len(params), len(buffers), training,
                     _amp_key())
        cache_key = _static_key(skel, params + buffers + arg_tensors,
                                key_extra)
        if cache_key in self._broken_keys:
            raise _EagerFallback
        with _break_key_scope(cache_key):
            entry = self._cache.get(cache_key)
            if entry is None:
                entry = self._build_forward(skel, params, buffers,
                                            len(arg_tensors))
                self._cache[cache_key] = entry
            jitted, n_buf, meta = entry
            if meta.get("uses_rng", True):
                rng_key = _random.next_key_spec()
            else:
                rng_key = _idle_rng_spec()

            ins = params + arg_tensors
            if n_buf:
                out = apply("to_static", lambda *arrs: jitted(
                    arrs[:len(params)],
                    [b._data for b in buffers],
                    arrs[len(params):], rng_key), ins, has_aux=True)
                out = list(out) if isinstance(out, tuple) else [out]
                # trailing aux outputs are the updated buffer values
                new_bufs = out[-n_buf:]
                outputs = out[:-n_buf]
                for b, nb in zip(buffers, new_bufs):
                    b._data = nb._data
                return _tree_rebuild(meta["out_skel"], outputs, lambda t: t)
            out = apply("to_static", lambda *arrs: jitted(
                arrs[:len(params)], [], arrs[len(params):], rng_key), ins)
            outputs = list(out) if isinstance(out, tuple) else [out]
            return _tree_rebuild(meta["out_skel"], outputs, lambda t: t)

    def _build_forward(self, skel, params, buffers, n_args):
        fn = self._fn
        meta = {}  # per-cache-entry output skeleton (set during trace)

        def pure(param_arrs, buf_arrs, arg_arrs, rng_spec):
            rng_key = _random.derive_key(rng_spec)
            saved = [(t, t._data) for t in params + buffers]
            saved_grads = [(t, t._grad) for t in params]
            try:
                for t, a in zip(params, param_arrs):
                    t._data = a
                for t, a in zip(buffers, buf_arrs):
                    t._data = a
                rebuilt_args, kw_items = _tree_rebuild(
                    skel, list(arg_arrs),
                    lambda a: Tensor(a, stop_gradient=True))
                with _random.trace_key_scope(rng_key):
                    out = fn(*rebuilt_args, **dict(kw_items))
                    meta["uses_rng"] = _random._trace_rng.counter > 0
                out_tensors: list = []
                meta["out_skel"] = _tree_flatten(out, out_tensors, [])
                out_arrs = tuple(t._data for t in out_tensors)
                new_bufs = tuple(b._data for b in buffers)
            finally:
                for t, a in saved:
                    t._data = a
                for t, g in saved_grads:
                    t._grad = g
            if buffers:
                return out_arrs, list(new_bufs)
            return out_arrs if len(out_arrs) > 1 else out_arrs[0]

        # NOTE: jax.jit caching keys on shapes; our cache keys on structure.
        return jax.jit(pure, static_argnums=()), len(buffers), meta

    # -- mode 2: whole train step (fwd+bwd+update) in one XLA program --
    def _fast_sig(self, args, kwargs, layers):
        """Cheap dispatch signature for the steady-state re-call: engages
        only for the plain ``step(x, y, ...)`` calling convention (bare
        Tensor positionals, no kwargs). Params/buffers/slots shapes are
        covered by the full key once at entry build and assumed stable
        thereafter (see :meth:`invalidate`)."""
        if kwargs:
            return None
        sig = []
        for a in args:
            if isinstance(a, Tensor):
                d = a._data
                sig.append((d.shape, d.dtype))
            else:
                return None
        return (tuple(sig), tuple(layer.training for layer in layers),
                _amp_key())

    def _call_whole_step(self, args, kwargs):
        fast = getattr(self, "_fast_step", None)
        st = getattr(self, "_state_cache", None)
        if fast and st is not None:
            if _struct_version()[0] != self._state_version:
                # some Layer somewhere gained/lost a param/sublayer since
                # the state walk: re-discover. If the captured module is
                # unchanged this re-memoizes without retracing.
                self.invalidate()
            else:
                sig = self._fast_sig(args, kwargs, st[3])
                hit = fast.get(sig) if sig is not None else None
                if hit is not None:
                    return self._exec_whole_step(hit, list(args), st)
        params, buffers, slots, layers, opts = self._state_cached()
        if not getattr(self, "_materialized", False):
            # accumulators are created lazily — materialize each optimizer's
            # state up front so the whole step stages without an eager warmup
            for opt in opts:
                if not opt._state_slots():
                    opt.materialize()
            self._materialized = True
            self._state_cache = None
            params, buffers, slots, layers, opts = self._state_cached()
        arg_tensors: list = []
        skel = _tree_flatten((args, tuple(sorted(kwargs.items()))),
                             arg_tensors, [])
        training = tuple(layer.training for layer in layers)
        # lr is a traced input (scalar array), so it is NOT part of the key
        key_extra = ("step", len(params), len(buffers), len(slots),
                     training, _amp_key())
        cache_key = _static_key(skel, params + buffers + arg_tensors,
                                key_extra)
        if cache_key in self._broken_keys:
            raise _EagerFallback
        entry = self._cache.get(cache_key)
        if entry is None:
            entry = self._build_whole_step(skel, params, buffers, slots,
                                           opts, len(arg_tensors))
            self._cache[cache_key] = entry
        with _break_key_scope(cache_key):  # tracing happens at this call
            out = self._exec_whole_step(entry, arg_tensors,
                                        (params, buffers, slots, layers,
                                         opts))
        # memoize AFTER a successful compiled execution so a graph-broken
        # signature can never land in the fast memo
        sig = self._fast_sig(args, kwargs, layers)
        if sig is not None:
            self._fast_step[sig] = entry
        return out

    def _exec_whole_step(self, entry, arg_tensors, state):
        """Steady-state step dispatch: build the state list, call the ONE
        compiled program, write results back. Zero eager device ops on the
        host side — the RNG key is derived in-program from a numpy spec
        (:func:`core.random.next_key_spec`) only when the traced step
        actually consumed randomness, and the learning rates ride a numpy
        array straight into the pjit call."""
        jitted, meta = entry
        params, buffers, slots, layers, opts = state
        if meta.get("uses_rng", True):
            rng_spec = _random.next_key_spec()
        else:
            rng_spec = _idle_rng_spec()
        # tpu-lint: ok[HS002] operands are python floats — host numpy rides into pjit with no device fetch (PR 7 zero-eager-op design)
        lrs = np.asarray([opt.get_lr() for opt in opts], np.float32)
        state_in = [t._data for t in params] + [b._data for b in buffers] + \
            [cont[k] for cont, k in slots]
        last = getattr(self, "_last_exec", None)
        if last is None or last[0] is not jitted:
            self._last_exec = (jitted, ([_aval(a) for a in state_in],
                                        [_aval(t._data) for t in
                                         arg_tensors],
                                        jax.ShapeDtypeStruct(
                                            (3,), jnp.uint32),
                                        jax.ShapeDtypeStruct(
                                            lrs.shape, jnp.float32)))
        out_arrs, new_state = jitted(state_in,
                                     [t._data for t in arg_tensors],
                                     rng_spec, lrs)
        if meta.get("unstaged_accumulators"):
            raise RuntimeError(
                "optimizer state was created during tracing and cannot be "
                f"staged: {sorted(meta['unstaged_accumulators'])}. Implement "
                "_materialize_param on the optimizer (see "
                "paddle_tpu/optimizer/optimizers.py) so its accumulators "
                "exist before compilation.")
        n_p, n_b = len(params), len(buffers)
        for t, a in zip(params, new_state[:n_p]):
            t._data = a
            t._grad = None
        for b, a in zip(buffers, new_state[n_p:n_p + n_b]):
            b._data = a
        for (cont, k), a in zip(slots, new_state[n_p + n_b:]):
            cont[k] = a
        return _tree_rebuild(meta["out_skel"], [
            Tensor(a, stop_gradient=True) for a in out_arrs], lambda t: t)

    def _build_whole_step(self, skel, params, buffers, slots, opts, n_args):
        fn = self._fn
        meta = {}  # per-cache-entry output skeleton (set during trace)

        def pure(state_arrs, arg_arrs, rng_spec, lrs):
            # the step key is derived IN-program from the uint32
            # [seed_hi, seed_lo, counter] spec — bit-identical to the
            # eager next_key(), but zero eager device ops per step
            rng_key = _random.derive_key(rng_spec)
            n_p, n_b = len(params), len(buffers)
            saved = [(t, t._data, t._grad) for t in params] + \
                [(b, b._data, None) for b in buffers]
            saved_slots = [(cont, k, cont[k]) for cont, k in slots]
            # snapshot accumulator keys so entries created DURING tracing
            # (e.g. an optimizer without _materialize_param) can be purged —
            # they would otherwise leak tracers into eager state
            acc_keys_before = [
                (opt, name, frozenset(per))
                for opt in opts
                for name, per in opt._accumulators.items()]
            try:
                for t, a in zip(params, state_arrs[:n_p]):
                    t._data = a
                    t._grad = None
                for b, a in zip(buffers, state_arrs[n_p:n_p + n_b]):
                    b._data = a
                for (cont, k), a in zip(slots, state_arrs[n_p + n_b:]):
                    cont[k] = a
                for i, opt in enumerate(opts):
                    opt._lr_override = lrs[i]
                rebuilt_args, kw_items = _tree_rebuild(
                    skel, list(arg_arrs),
                    lambda a: Tensor(a, stop_gradient=True))
                with _random.trace_key_scope(rng_key):
                    out = fn(*rebuilt_args, **dict(kw_items))
                    # consumed trace keys mean the step needs a FRESH spec
                    # per call; otherwise dispatch reuses one idle spec
                    meta["uses_rng"] = _random._trace_rng.counter > 0
                out_tensors: list = []
                meta["out_skel"] = _tree_flatten(out, out_tensors, [])
                out_arrs = tuple(t._data for t in out_tensors)
                new_state = [t._data for t in params] + \
                    [b._data for b in buffers] + \
                    [cont[k] for cont, k in slots]
            finally:
                for t, a, g in saved:
                    t._data = a
                    t._grad = g
                for cont, k, v in saved_slots:
                    cont[k] = v
                for opt in opts:
                    opt._lr_override = None
                    for name, per in list(opt._accumulators.items()):
                        before = next(
                            (ks for o, n, ks in acc_keys_before
                             if o is opt and n == name), frozenset())
                        for k in list(per):
                            if k not in before:
                                del per[k]  # purge tracer created in trace
                                meta.setdefault("unstaged_accumulators",
                                                set()).add(
                                    (type(opt).__name__, name))
            return out_arrs, new_state

        donate = (0,) if self._donate_state else ()
        return jax.jit(pure, donate_argnums=donate), meta

    def aot_compile(self, *args, **kwargs):
        """Trace + XLA-compile the whole-step program for these example
        inputs WITHOUT executing it. Returns the jax Compiled object —
        ``.memory_analysis()`` gives per-device argument/temp/output bytes,
        so an N-billion-param config's HBM footprint is checkable on a
        virtual CPU mesh before any chip time (reference capability:
        memory estimation tools, auto_parallel cost model memory pass)."""
        if self._capture is None:
            raise RuntimeError("aot_compile requires whole-step staging "
                               "(capture=(model, optimizer))")
        params, buffers, slots, layers, opts = self._state()
        if not getattr(self, "_materialized", False):
            for opt in opts:
                if not opt._state_slots():
                    opt.materialize()
            self._materialized = True
            params, buffers, slots, layers, opts = self._state()
        arg_tensors: list = []
        skel = _tree_flatten((args, tuple(sorted(kwargs.items()))),
                             arg_tensors, [])
        jitted, meta = self._build_whole_step(skel, params, buffers, slots,
                                              opts, len(arg_tensors))

        state_avals = [_aval(t._data) for t in params] + \
            [_aval(b._data) for b in buffers] + \
            [_aval(cont[k]) for cont, k in slots]
        arg_avals = [_aval(t._data) for t in arg_tensors]
        rng_aval = jax.ShapeDtypeStruct((3,), jnp.uint32)
        lrs_aval = jax.ShapeDtypeStruct((max(len(opts), 1),), jnp.float32)
        return jitted.lower(state_avals, arg_avals, rng_aval,
                            lrs_aval).compile()

    def compiled_text(self):
        """Optimized-HLO text of the most recent whole-step call. Lets tests
        assert on the collectives GSPMD actually inserted (reduce-scatter
        for ZeRO-2 grads, all-gather-on-use for ZeRO-3 params, no weight
        all-gather under TP) instead of trusting the sharding annotations."""
        if not hasattr(self, "_last_exec"):
            raise RuntimeError(
                "call the to_static function once before compiled_text()")
        jitted, args = self._last_exec
        return jitted.lower(*args).compile().as_text()

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"

    def concrete_program(self):  # reference-API stub for introspection
        return self._cache


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, capture=None, **kwargs):
    """Reference: python/paddle/jit/api.py:171 (paddle.jit.to_static).

    ``full_graph=False`` (default, reference SOT semantics) falls back to
    eager with a warning when tracing hits an unstageable construct;
    ``full_graph=True`` makes that a hard error.

    ``capture=(model, optimizer, ...)`` enables whole-train-step staging —
    see module docstring."""
    def decorate(fn):
        from ..nn import Layer
        if isinstance(fn, Layer):
            static = StaticFunction(fn, input_spec, capture,
                                    full_graph=full_graph)
            fn.forward = static
            return fn
        return StaticFunction(fn, input_spec, capture,
                              full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None
