"""jit.save / jit.load — AOT export of compiled models.

Reference: python/paddle/jit/api.py (jit.save) + translated_layer.py
(jit.load). TPU-native: the traced forward is serialized as a StableHLO
artifact via jax.export (the deployment story that replaces
ProgramDesc+inference predictor); parameters ride alongside as a pickled
state (``.pdiparams``), so the artifact is retrainable-free but reloadable
anywhere jax runs (including the AOT serving path).

Files written for path prefix P:
  P.pdmodel    — serialized StableHLO (jax.export artifact)
  P.pdiparams  — pickled parameter/buffer arrays (framework.io format)
  P.pdmeta     — pickled structure metadata (output skeleton, input specs)
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..core import random as _random
from ..core.tensor import Tensor
from .api import InputSpec, StaticFunction, _tree_flatten, _tree_rebuild

__all__ = ["save", "load", "TranslatedLayer"]


def save(layer, path, input_spec=None, **configs):
    """Reference: paddle.jit.save (jit/api.py)."""
    from ..nn import Layer
    if isinstance(layer, Layer):
        fn = layer.forward
        static = fn if isinstance(fn, StaticFunction) else \
            StaticFunction(layer)
    elif isinstance(layer, StaticFunction):
        static = layer
    else:
        static = StaticFunction(layer)
    if input_spec is None:
        raise ValueError(
            "jit.save requires input_spec=[InputSpec(...)] to define the "
            "exported signature (reference: jit.save input_spec)")
    specs = [s if isinstance(s, InputSpec) else InputSpec(
        list(s.shape), s.dtype) for s in input_spec]

    params, buffers, _, layers, _ = static._state()
    state_tensors = params + buffers
    was_training = [lyr.training for lyr in layers]
    for lyr in layers:
        lyr.eval()  # export inference graph (dropout off, BN in eval mode)
    meta = {}
    try:
        fn = static._fn

        def pure(state_arrs, arg_arrs):
            saved = [(t, t._data) for t in state_tensors]
            try:
                for t, a in zip(state_tensors, state_arrs):
                    t._data = a
                args = [Tensor(a, stop_gradient=True) for a in arg_arrs]
                with _random.trace_key_scope(jax.random.key(0)):
                    out = fn(*args)
                out_tensors: list = []
                meta["out_skel"] = _tree_flatten(out, out_tensors, [])
                return tuple(t._data for t in out_tensors)
            finally:
                for t, a in saved:
                    t._data = a

        state_shapes = [jax.ShapeDtypeStruct(tuple(t.shape), t._data.dtype)
                        for t in state_tensors]
        arg_shapes = _arg_shapes(specs)
        exported = jax_export.export(jax.jit(pure))(state_shapes, arg_shapes)
        blob = exported.serialize()
    finally:
        for lyr, tr in zip(layers, was_training):
            lyr.training = tr
            if tr:
                lyr.train()

    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    state_np = [np.asarray(t._data) for t in state_tensors]
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state_np, f, protocol=4)
    meta.update({
        "n_state": len(state_tensors),
        "input_specs": [(s.shape, str(np.dtype(s.dtype))) for s in specs],
    })
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f, protocol=4)


def _arg_shapes(specs):
    """InputSpec shapes → ShapeDtypeStructs; ``None`` dims export as
    symbolic dimensions (reference: dynamic-shape InputSpec in jit.save).
    Axis-0 ``None``s share one 'batch' symbol across every input (batch
    dims must agree between inputs of one forward); other ``None`` axes
    get independent symbols."""
    if not any(d is None for s in specs for d in s.shape):
        return [jax.ShapeDtypeStruct(tuple(int(d) for d in s.shape),
                                     s.dtype) for s in specs]
    scope = jax_export.SymbolicScope()
    shapes = []
    fresh = 0
    for s in specs:
        if not any(d is None for d in s.shape):
            shapes.append(jax.ShapeDtypeStruct(
                tuple(int(d) for d in s.shape), s.dtype))
            continue
        names = []
        for ax, d in enumerate(s.shape):
            if d is None:
                if ax == 0:
                    names.append("batch")
                else:
                    names.append(f"dyn{fresh}")
                    fresh += 1
            else:
                names.append(str(int(d)))
        dims = jax_export.symbolic_shape(", ".join(names), scope=scope)
        shapes.append(jax.ShapeDtypeStruct(dims, s.dtype))
    return shapes


class TranslatedLayer:
    """Runnable deserialized model (reference: jit/translated_layer.py).
    Behaves like an eval-mode Layer: call it with Tensors, get Tensors."""

    def __init__(self, exported, state, meta):
        self._exported = exported
        self._state = state
        self._meta = meta
        self.training = False

    def __call__(self, *args):
        arg_arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in args]
        out = self._exported.call(self._state, arg_arrs)
        out = list(out) if isinstance(out, (tuple, list)) else [out]
        return _tree_rebuild(self._meta["out_skel"],
                             [Tensor(o, stop_gradient=True) for o in out],
                             lambda t: t)

    def forward(self, *args):
        return self(*args)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError(
            "a jit.load-ed TranslatedLayer is an inference artifact; "
            "re-train from the original Layer + state_dict instead")


def load(path, **configs) -> TranslatedLayer:
    """Reference: paddle.jit.load."""
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        state_np = pickle.load(f)
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    state = [jnp.asarray(a) for a in state_np]
    return TranslatedLayer(exported, state, meta)
