"""paddle_tpu.jit — trace-to-XLA compilation (replaces dy2static/SOT/PIR/CINN).

Reference namespace: python/paddle/jit/__init__.py.
"""
from .api import (  # noqa: F401
    InputSpec, StaticFunction, ignore_module, not_to_static, to_static,
)
from .control_flow import (  # noqa: F401
    case, cond, scan_loop, switch_case, while_loop,
)
from .save_load import TranslatedLayer, load, save  # noqa: F401
