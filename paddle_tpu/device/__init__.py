"""paddle_tpu.device — device management namespace.

Reference: python/paddle/device/__init__.py (set_device:265, cuda/xpu
namespaces, streams/events). TPU-native: devices are PjRt devices; memory
stats come from PjRt allocator telemetry; stream/event synchronization
collapses into `block_until_ready` (XLA programs are ordered per device, so
explicit stream management is compiled away).
"""
from __future__ import annotations

import jax

from ..core.device import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_all_devices, get_device,
    get_place, is_compiled_with_tpu, set_device,
)

__all__ = ["set_device", "get_device", "get_all_devices", "device_count",
           "Place", "TPUPlace", "CPUPlace", "is_compiled_with_tpu",
           "synchronize", "memory_stats", "max_memory_allocated",
           "max_memory_reserved", "memory_allocated", "memory_reserved",
           "cuda", "Stream", "Event", "stream_guard", "current_stream"]


def _dev(device=None):
    from ..core.device import _platform_of
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):
        kind, _, idx = device.partition(":")
        want = "cpu" if kind == "cpu" else "tpu"
        devs = [d for d in jax.devices() if _platform_of(d) == want]
        if not devs and want == "cpu":
            devs = jax.devices("cpu")
        if not devs:
            raise RuntimeError(f"no {kind} device attached")
        i = int(idx) if idx else 0
        if i >= len(devs):
            raise RuntimeError(
                f"device index {i} out of range for {kind} "
                f"({len(devs)} attached)")
        return devs[i]
    return device


def synchronize(device=None):
    """Block until all queued work on the device finished (reference:
    paddle.device.synchronize). In jax: a tiny transfer forces a sync."""
    import jax.numpy as jnp
    jnp.zeros((), device=_dev(device)).block_until_ready()


def memory_stats(device=None):
    d = _dev(device)
    stats = d.memory_stats() if hasattr(d, "memory_stats") else None
    return stats or {}


def memory_allocated(device=None):
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    s = memory_stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None):
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_limit", 0)))


def max_memory_reserved(device=None):
    return memory_reserved(device)


class Stream:
    """Compatibility shim: XLA serializes per-device execution, so explicit
    streams are a no-op container (reference: device/cuda/streams.py).
    Device resolution is lazy — importing the module must not touch the
    backend."""

    def __init__(self, device=None, priority=2):
        self._device_arg = device

    @property
    def device(self):
        return _dev(self._device_arg)

    def synchronize(self):
        synchronize(self.device)

    def wait_stream(self, stream):
        pass

    def wait_event(self, event):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True

    def synchronize(self):
        pass


_current_stream = None


def current_stream(device=None):
    global _current_stream
    if _current_stream is None:
        _current_stream = Stream()
    return _current_stream


import contextlib as _contextlib


@_contextlib.contextmanager
def stream_guard(stream):
    yield


class _CudaNamespace:
    """paddle.device.cuda compatibility surface, routed to the TPU backend
    (the reference exposes these under device/cuda/)."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return max_memory_reserved(device)

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def get_device_properties(device=None):
        d = _dev(device)
        return {"name": d.device_kind, "platform": d.platform}


cuda = _CudaNamespace()
