"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas/pjit.

Public surface mirrors ``import paddle`` (reference: python/paddle/__init__.py):
tensors + ~200 ops, nn, optimizer, amp, autograd, io, jit, distributed, with
eager (dygraph) semantics over XLA and trace-to-HLO compilation replacing the
static-graph/PIR/CINN stack.
"""
from __future__ import annotations

__version__ = "0.1.0"

# ---- tpu-lint boot fast-path (ISSUE 12) ------------------------------------
# `python -m paddle_tpu.tools.analyze` must scan the tree WITHOUT importing
# jax: runpy imports this package before the analyzer's __main__ gets
# control, so the only place to skip framework init is here. The boot shape
# is detected from the interpreter command line (during parent-package
# import under `-m`, sys.argv[0] is still the '-m' placeholder and
# /proc/self/cmdline names the target module); anything else — including
# every other `-m` target — initializes normally. Hosts without procfs
# fall back to full init (the CLI still works, just not jax-free);
# PADDLE_TPU_LINT_BOOT=1 is the portable override.


def _tpu_lint_boot() -> bool:
    import os as _os
    import sys as _sys
    if _os.environ.get("PADDLE_TPU_LINT_BOOT") == "1":
        return True
    if not _sys.argv or _sys.argv[0] != "-m":
        return False
    try:
        with open("/proc/self/cmdline", "rb") as _f:
            _argv = _f.read().split(b"\0")
    except OSError:
        return False
    for _i, _tok in enumerate(_argv):
        if _tok == b"paddle_tpu.tools.analyze" and _i and _argv[_i - 1] == b"-m":
            return True
        if _tok == b"-mpaddle_tpu.tools.analyze":
            return True
    return False


_TPU_LINT_BOOT = _tpu_lint_boot()

if not _TPU_LINT_BOOT:
    # the entire framework surface assembles below; the tpu-lint boot leaves
    # paddle_tpu a stub package so paddle_tpu.tools.analyze imports jax-free

    import jax as _jax

    # Paddle's dtype surface includes int64/float64 as first-class (int64 is the
    # default index dtype); enable x64 so those dtypes exist. Perf-critical paths
    # use bf16/f32 explicitly, so TPU speed is unaffected.
    _jax.config.update("jax_enable_x64", True)

    # older jax runtimes lack top-level shard_map: publish the alias BEFORE any
    # submodule does `from jax import shard_map`
    from .core import jax_compat as _jax_compat  # noqa: E402

    _jax_compat.install()

    from .core.autograd import enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
    from .core.device import (  # noqa: F401
        CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
        set_device,
    )
    from .core.dtype import (  # noqa: F401
        bfloat16, bool_ as bool8, complex64, complex128, float16, float32, float64,
        get_default_dtype, int8, int16, int32, int64, set_default_dtype, uint8,
    )
    from .core.random import get_rng_state, seed, set_rng_state  # noqa: F401
    from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
    from .ops import *  # noqa: F401,F403
    from .ops import einsum, one_hot  # noqa: F401

    from . import amp  # noqa: F401
    from . import audio  # noqa: F401
    from . import autograd  # noqa: F401
    from . import fft  # noqa: F401
    from . import framework  # noqa: F401
    from . import inference  # noqa: F401
    from . import io  # noqa: F401
    from . import jit  # noqa: F401
    from . import linalg  # noqa: F401
    from . import nn  # noqa: F401
    from . import optimizer  # noqa: F401
    from . import regularizer  # noqa: F401
    from . import signal  # noqa: F401
    from . import utils  # noqa: F401
    from . import version  # noqa: F401
    from .version import full_version as __version__  # noqa: F401
    from . import distributed  # noqa: F401
    from . import distribution  # noqa: F401
    from . import hapi  # noqa: F401
    from . import observability  # noqa: F401
    from . import serving  # noqa: F401
    from . import metric  # noqa: F401
    from . import models  # noqa: F401
    from . import profiler  # noqa: F401
    from .hapi import Model  # noqa: F401
    from .hapi.summary import summary  # noqa: F401
    from .hapi.dynamic_flops import flops  # noqa: F401
    from .framework.io import load, save  # noqa: F401
    from .framework.param_attr import ParamAttr  # noqa: F401
    from .framework.dtype_info import (  # noqa: F401
        finfo, iinfo, is_complex, is_floating_point, is_integer,
    )
    from .framework.compat import (  # noqa: F401
        LazyGuard, batch, check_shape, create_parameter, get_cuda_rng_state,
        set_cuda_rng_state,
    )
    from . import geometric  # noqa: F401
    from . import hub  # noqa: F401

    # paddle aliases
    bool = bool8  # noqa: A001


    def disable_static(place=None):
        from .static.program import disable_static_mode
        disable_static_mode()
        return None


    def enable_static():
        """Reference: paddle.enable_static — switch to Program recording.
        Ops on paddle.static.data() variables append to the default main
        Program; Executor.run(feed/fetch) evaluates it (static/program.py)."""
        from .static.program import enable_static_mode
        enable_static_mode()


    def in_dynamic_mode():
        from .static.program import in_static_mode
        return not in_static_mode()


    def set_printoptions(precision=None, threshold=None, edgeitems=None,
                         sci_mode=None, linewidth=None):
        """Reference: paddle.set_printoptions — forwards to numpy's print
        options (Tensor repr renders through numpy)."""
        import numpy as _np
        kw = {}
        if precision is not None:
            kw["precision"] = precision
        if threshold is not None:
            kw["threshold"] = threshold
        if edgeitems is not None:
            kw["edgeitems"] = edgeitems
        if linewidth is not None:
            kw["linewidth"] = linewidth
        if sci_mode is not None:
            kw["suppress"] = not sci_mode
        _np.set_printoptions(**kw)


    def disable_signal_handler():
        """Reference parity no-op: the jax runtime installs no paddle-style
        signal handlers to disable."""
        return None


    def is_compiled_with_cuda():
        return False  # TPU-native build


    def is_compiled_with_xpu():
        return False


    def is_compiled_with_cinn():
        return False  # XLA plays CINN's role


    def is_compiled_with_rocm():
        return False


    def is_compiled_with_custom_device(device_type="tpu"):
        return device_type in ("tpu", "axon")  # PjRt TPU is the device


    def is_grad_enabled_():
        return is_grad_enabled()


    from .framework.flags import get_flags, set_flags  # noqa: F401,E402
    from . import incubate  # noqa: F401,E402
    from . import vision  # noqa: F401,E402
    from . import static  # noqa: F401,E402
    from . import device  # noqa: F401,E402
    from . import text  # noqa: F401,E402
    from . import onnx  # noqa: F401,E402
    from . import quantization  # noqa: F401,E402
    from . import sparse  # noqa: F401,E402
    from . import strings  # noqa: F401,E402

    # bind the tensor methods that need the fully-assembled namespace
    from .core.tensor import Tensor as _T  # noqa: E402
    _T._late_bind()
    del _T

    # InferMeta preflights: paddle-style shape/dtype errors before XLA
    # (reference: phi/infermeta/*) — wraps the assembled namespaces, so last
    from .core import infermeta as _infermeta  # noqa: E402
    _infermeta.install()
    del _infermeta
