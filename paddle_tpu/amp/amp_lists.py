"""AMP op lists (reference: python/paddle/amp/amp_lists.py and
paddle/fluid/eager/amp_utils.h semantics).

White = numerically safe + MXU-profitable in low precision (matmul-class).
Black = keep f32 (reductions / exp-log / losses / norm statistics).
Names match the op names passed to core.dispatch.apply.
"""

WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "einsum", "bmm", "mm", "addmm",
    "scaled_dot_product_attention", "flash_attention",
}

BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "expm1", "pow", "square",
    "sqrt", "rsqrt", "sum", "mean", "prod", "std", "var", "logsumexp",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "nll_loss", "bce", "bce_with_logits", "kl_div", "mse_loss", "l1_loss",
    "smooth_l1", "margin_ranking", "layer_norm", "batch_norm", "group_norm",
    "instance_norm", "rms_norm", "norm", "cumsum", "cumprod", "renorm",
    "cosine_similarity", "sigmoid_focal_loss", "softplus", "erf", "erfinv",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "asinh",
    "acosh", "atanh", "reciprocal",
}
