"""GradScaler — dynamic loss scaling (reference:
python/paddle/amp/grad_scaler.py:578).

On TPU the default amp dtype is bf16 (same exponent range as f32), so scaling
is usually a no-op — but the full f16 semantics (dynamic scale, inf/nan skip,
growth/backoff) are kept for API and numerics parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        self._stepped = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _grads_of(self, optimizer):
        return [p for p in optimizer._parameter_list if p._grad is not None]

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        finite_flags = []
        for p in self._grads_of(optimizer):
            g = p._grad
            finite_flags.append(jnp.all(jnp.isfinite(g)))
            p._grad = (g.astype(jnp.float32) * inv).astype(g.dtype)
        # one fused reduction + one host sync for the whole parameter set
        self._found_inf = bool(finite_flags) and not bool(
            jnp.all(jnp.stack(finite_flags)))
        self._unscaled = True

    def step(self, optimizer):
        """unscale + skip-on-inf + optimizer.step. Like the reference
        (python/paddle/amp/grad_scaler.py), step() does NOT update the loss
        scale — the canonical pattern is ``scaler.step(opt);
        scaler.update()``; use minimize() for the fused form."""
        if not self._enable:
            optimizer.step()
            return
        if self._stepped:
            raise RuntimeError(
                "GradScaler.step() has already been called since the last "
                "update(); call scaler.update() first.")
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._stepped = True

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            self._found_inf = False
            self._unscaled = False
            self._stepped = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._unscaled = False
        self._stepped = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, value):
        self._scale = float(value)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)

    set_state_dict = load_state_dict


AmpScaler = GradScaler
