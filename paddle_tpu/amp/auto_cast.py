"""auto_cast — automatic mixed precision (reference:
python/paddle/amp/auto_cast.py:273 amp_guard).

TPU-native: bf16 is the native low precision (MXU); the autocast decision is
made once per eager op inside core.dispatch.apply, mirroring the reference's
eager_amp_auto_cast.h hook placement. O1 casts white-list ops to the amp dtype
and black-list ops to f32; O2 casts everything except the black list.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core.dtype import convert_dtype
from . import amp_lists

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_state"]


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.white = amp_lists.WHITE_LIST
        self.black = amp_lists.BLACK_LIST


_state = _AmpState()


def amp_state():
    return _state


def amp_key_cached():
    """Hashable fingerprint of the active autocast state, cached per state
    identity. auto_cast REPLACES the white/black sets on entry (never
    mutates in place), so keying the cache on their ids is sound — the
    per-call cost collapses to one tuple compare instead of two frozenset
    builds (this sits on the staged-train-step dispatch path)."""
    st = _state
    cached = getattr(st, "_key_cache", None)
    if cached is not None:
        enabled, dtype, level, white, black, key = cached
        # identity (`is`) on the PINNED objects — an id() compare would
        # accept a recycled id after the old sets were freed and hand a
        # compile cache the wrong autocast fingerprint
        if enabled == st.enabled and dtype is st.dtype \
                and level == st.level and white is st.white \
                and black is st.black:
            return key
    key = (st.enabled, str(st.dtype), st.level,
           frozenset(st.white), frozenset(st.black))
    st._key_cache = (st.enabled, st.dtype, st.level, st.white, st.black,
                     key)
    return key


_EXEMPT = {"cast", "clone", "getitem", "setitem", "assign"}


def amp_dtype_for(op_name: str):
    """Called by dispatch.apply: returns the target dtype for this op's
    floating inputs, or None to leave them untouched."""
    if not _state.enabled or op_name in _EXEMPT:
        return None
    if op_name in _state.black:
        return jnp.float32
    if _state.level == "O2":
        return _state.dtype
    if op_name in _state.white:
        return _state.dtype
    return None


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """Reference: paddle.amp.auto_cast (amp/auto_cast.py:273)."""
    assert level in ("O0", "O1", "O2"), f"bad amp level {level}"
    prev = (_state.enabled, _state.dtype, _state.level, _state.white,
            _state.black)
    _state.enabled = bool(enable) and level != "O0"
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    white = set(amp_lists.WHITE_LIST)
    black = set(amp_lists.BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    _state.white = white
    _state.black = black
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.white,
         _state.black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration (reference: paddle.amp.decorate): cast model params to
    the amp dtype and turn on optimizer master weights."""
    assert level in ("O1", "O2")
    single_model = not isinstance(models, (list, tuple))
    single_opt = optimizers is not None and not isinstance(
        optimizers, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is not None:
        opt_list = [optimizers] if single_opt else list(optimizers)
        for opt in opt_list:
            if master_weight is not False:
                opt._multi_precision = True
        if single_model and single_opt:
            return models, optimizers
        return model_list, opt_list
    return models if single_model else model_list
