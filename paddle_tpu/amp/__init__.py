"""paddle_tpu.amp — automatic mixed precision.

Reference namespace: python/paddle/amp/__init__.py (auto_cast
amp/auto_cast.py:273, GradScaler amp/grad_scaler.py:578). bf16 is the TPU
default low-precision dtype.
"""
from . import amp_lists  # noqa: F401
from . import debugging  # noqa: F401
from .auto_cast import amp_guard, auto_cast, decorate  # noqa: F401
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401
