"""paddle.amp.debugging — numerics checking + operator statistics.

Reference: python/paddle/amp/debugging.py (DebugMode, check_numerics,
TensorCheckerConfig, enable/disable_tensor_checker, operator stats
collection, compare_accuracy). TPU-native: the checks ride the dispatch
hooks (the same seam as FLAGS_check_nan_inf) and XLA's debug_nans; op
statistics reuse the profiler's host op tracer aggregation.
"""
from __future__ import annotations

import contextlib
from enum import Enum

import numpy as np

import jax.numpy as jnp

__all__ = ["DebugMode", "TensorCheckerConfig", "check_numerics",
           "enable_tensor_checker", "disable_tensor_checker",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "compare_accuracy", "check_layer_numerics"]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class TensorCheckerConfig:
    """Reference: debugging.py TensorCheckerConfig."""

    def __init__(self, enable=True,
                 debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        self.debug_step = debug_step


def check_numerics(tensor, op_type="", var_name="",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Reference: debugging.py check_numerics — returns
    (num_nan, num_inf, num_zero) as tensors; aborts on NaN/Inf when the
    mode says so."""
    from ..core.tensor import Tensor
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    n_nan = jnp.sum(jnp.isnan(arr)).astype(jnp.int64)
    n_inf = jnp.sum(jnp.isinf(arr)).astype(jnp.int64)
    n_zero = jnp.sum(arr == 0).astype(jnp.int64)
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        bad = int(n_nan) + int(n_inf)
        if bad:
            raise FloatingPointError(
                f"(check_numerics) op={op_type!r} var={var_name!r}: "
                f"{int(n_nan)} NaN, {int(n_inf)} Inf values")
    return (Tensor(n_nan, stop_gradient=True),
            Tensor(n_inf, stop_gradient=True),
            Tensor(n_zero, stop_gradient=True))


_checker_config = None
_checker_hook_installed = False


def _checker_hook(name, t0, t1, inputs, result=None):
    cfg = _checker_config
    if cfg is None or not cfg.enable or result is None:
        return
    if cfg.checked_op_list and name not in cfg.checked_op_list:
        return
    if name in cfg.skipped_op_list:
        return
    from ..core.tensor import Tensor
    import jax as _jax
    res = result if isinstance(result, (tuple, list)) else (result,)
    for r in res:
        arr = getattr(r, "_data", None)
        if arr is None or isinstance(arr, _jax.core.Tracer) or \
                not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if not bool(jnp.all(jnp.isfinite(arr))):
            msg = f"(tensor_checker) op '{name}' produced NaN/Inf"
            if cfg.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
                raise FloatingPointError(msg)
            print(msg)


def enable_tensor_checker(checker_config):
    """Reference: debugging.py enable_tensor_checker — every dispatched
    op's outputs are checked for NaN/Inf per the config."""
    global _checker_config
    from ..core import dispatch as _dispatch
    _checker_config = checker_config
    prev = _dispatch._op_profiler

    def chained(name, t0, t1, inputs, result=None):
        if prev is not None:
            prev(name, t0, t1, inputs, result)
        _checker_hook(name, t0, t1, inputs, result)

    chained._tensor_checker = True
    chained._prev = prev
    _dispatch._op_profiler = chained


def disable_tensor_checker():
    global _checker_config
    from ..core import dispatch as _dispatch
    hook = _dispatch._op_profiler
    if hook is not None and getattr(hook, "_tensor_checker", False):
        _dispatch._op_profiler = hook._prev
    _checker_config = None


_op_stats = None


def enable_operator_stats_collection():
    """Reference: debugging.py — count dispatched ops per dtype."""
    global _op_stats
    from ..core import dispatch as _dispatch
    _op_stats = {}
    prev = _dispatch._op_profiler

    def hook(name, t0, t1, inputs, result=None):
        if prev is not None:
            prev(name, t0, t1, inputs, result)
        dt = ""
        for t in inputs:
            d = getattr(t, "dtype", None)
            if d is not None:
                dt = str(d)
                break
        key = (name, dt)
        _op_stats[key] = _op_stats.get(key, 0) + 1

    hook._op_stats = True
    hook._prev = prev
    _dispatch._op_profiler = hook


def disable_operator_stats_collection():
    from ..core import dispatch as _dispatch
    hook = _dispatch._op_profiler
    if hook is not None and getattr(hook, "_op_stats", False):
        _dispatch._op_profiler = hook._prev
    stats = _op_stats or {}
    if stats:
        print("<------- op list (op, dtype, calls) ------->")
        for (name, dt), n in sorted(stats.items()):
            print(f"  {name:30s} {dt:18s} {n}")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Reference: debugging.py compare_accuracy — compare two runs' saved
    tensor dumps (paddle.save'd dicts of name->Tensor) and write a csv of
    per-tensor max abs/rel error."""
    from .. import load as _load
    a = _load(dump_path)
    b = _load(another_dump_path)
    rows = ["tensor,max_abs_err,max_rel_err"]
    for k in sorted(set(a) & set(b)):
        va = np.asarray(a[k].numpy() if hasattr(a[k], "numpy") else a[k],
                        np.float64)
        vb = np.asarray(b[k].numpy() if hasattr(b[k], "numpy") else b[k],
                        np.float64)
        if va.shape != vb.shape:
            rows.append(f"{k},shape-mismatch,shape-mismatch")
            continue
        abs_err = np.max(np.abs(va - vb)) if va.size else 0.0
        rel = np.max(np.abs(va - vb) / (np.abs(vb) + 1e-12)) \
            if va.size else 0.0
        rows.append(f"{k},{abs_err:.6e},{rel:.6e}")
    with open(output_filename, "w") as f:
        f.write("\n".join(rows) + "\n")
    return output_filename


def check_layer_numerics(func):
    """Reference: debugging.py check_layer_numerics decorator — wraps a
    Layer.forward so inputs/outputs are numerics-checked."""
    import functools

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        for i, a in enumerate(args):
            if hasattr(a, "_data"):
                check_numerics(a, op_type=type(self).__name__,
                               var_name=f"input{i}")
        out = func(self, *args, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for i, o in enumerate(outs):
            if hasattr(o, "_data"):
                check_numerics(o, op_type=type(self).__name__,
                               var_name=f"output{i}")
        return out

    return wrapper
