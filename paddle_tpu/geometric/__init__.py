"""paddle.geometric — graph learning primitives.

Reference: python/paddle/geometric/ (math.py segment ops over
phi segment_pool kernels; message_passing/send_recv.py graph_send_recv;
reindex.py; sampling/neighbors.py). TPU-native: segment reductions map
onto jax.ops.segment_* (XLA scatter-reduce, MXU-friendly dense gathers for
message passing); graph sampling is host-side (numpy) exactly like the
reference's CPU sampling kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_min", "segment_max", "reindex_graph",
           "sample_neighbors"]


def _nseg(index, count):
    if count is not None:
        return int(count)
    idx = index._data if isinstance(index, Tensor) else index
    return int(jnp.max(idx)) + 1 if idx.size else 0


def segment_sum(data, segment_ids, name=None):
    """Reference: geometric/math.py segment_sum (phi segment_pool SUM)."""
    n = _nseg(segment_ids, None)
    return apply("segment_sum",
                 lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                 [data, segment_ids])


def segment_mean(data, segment_ids, name=None):
    """Reference: geometric/math.py segment_mean."""
    n = _nseg(segment_ids, None)

    def fwd(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), i,
                                num_segments=n)
        shape = (n,) + (1,) * (d.ndim - 1)
        return s / jnp.maximum(c.reshape(shape), 1)

    return apply("segment_mean", fwd, [data, segment_ids])


def segment_min(data, segment_ids, name=None):
    """Reference: geometric/math.py segment_min (empty segments -> 0)."""
    n = _nseg(segment_ids, None)

    def fwd(d, i):
        m = jax.ops.segment_min(d, i, num_segments=n)
        filled = jax.ops.segment_sum(jnp.ones((d.shape[0],), jnp.float32),
                                     i, num_segments=n) > 0
        shape = (n,) + (1,) * (d.ndim - 1)
        return jnp.where(filled.reshape(shape), m, 0).astype(d.dtype)

    return apply("segment_min", fwd, [data, segment_ids])


def segment_max(data, segment_ids, name=None):
    """Reference: geometric/math.py segment_max (empty segments -> 0)."""
    n = _nseg(segment_ids, None)

    def fwd(d, i):
        m = jax.ops.segment_max(d, i, num_segments=n)
        filled = jax.ops.segment_sum(jnp.ones((d.shape[0],), jnp.float32),
                                     i, num_segments=n) > 0
        shape = (n,) + (1,) * (d.ndim - 1)
        return jnp.where(filled.reshape(shape), m, 0).astype(d.dtype)

    return apply("segment_max", fwd, [data, segment_ids])


_POOLS = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
          "max": jax.ops.segment_max}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Reference: geometric/message_passing/send_recv.py send_u_recv
    (graph_send_recv kernel): gather x at src, reduce at dst."""
    n = out_size if out_size is not None else \
        (x.shape[0] if hasattr(x, "shape") else None)
    n = int(n)
    op = reduce_op.lower()

    def fwd(xv, si, di):
        msg = jnp.take(xv, si, axis=0)
        if op == "mean":
            s = jax.ops.segment_sum(msg, di, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones((msg.shape[0],), xv.dtype),
                                    di, num_segments=n)
            shape = (n,) + (1,) * (xv.ndim - 1)
            return s / jnp.maximum(c.reshape(shape), 1)
        out = _POOLS[op](msg, di, num_segments=n)
        if op in ("min", "max"):
            filled = jax.ops.segment_sum(
                jnp.ones((msg.shape[0],), jnp.float32), di,
                num_segments=n) > 0
            shape = (n,) + (1,) * (xv.ndim - 1)
            out = jnp.where(filled.reshape(shape), out, 0).astype(xv.dtype)
        return out

    return apply("send_u_recv", fwd, [x, src_index, dst_index])


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Reference: send_ue_recv — combine node features with edge features
    (add/sub/mul/div) before the dst reduction."""
    n = int(out_size if out_size is not None else x.shape[0])
    mop = message_op.lower()
    rop = reduce_op.lower()
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[mop]

    def fwd(xv, ev, si, di):
        msg = combine(jnp.take(xv, si, axis=0), ev)
        if rop == "mean":
            s = jax.ops.segment_sum(msg, di, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones((msg.shape[0],), msg.dtype),
                                    di, num_segments=n)
            shape = (n,) + (1,) * (msg.ndim - 1)
            return s / jnp.maximum(c.reshape(shape), 1)
        out = _POOLS[rop](msg, di, num_segments=n)
        if rop in ("min", "max"):
            filled = jax.ops.segment_sum(
                jnp.ones((msg.shape[0],), jnp.float32), di,
                num_segments=n) > 0
            shape = (n,) + (1,) * (msg.ndim - 1)
            out = jnp.where(filled.reshape(shape), out, 0).astype(msg.dtype)
        return out

    return apply("send_ue_recv", fwd, [x, y, src_index, dst_index])


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Reference: send_uv — per-edge combination of src and dst features."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op.lower()]
    return apply("send_uv",
                 lambda xv, yv, si, di: combine(jnp.take(xv, si, axis=0),
                                                jnp.take(yv, di, axis=0)),
                 [x, y, src_index, dst_index])


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Reference: geometric/reindex.py reindex_graph (CPU kernel): compact
    the union of x and neighbors to local ids."""
    xs = np.asarray(x._data if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors._data if isinstance(neighbors, Tensor)
                    else neighbors)
    ct = np.asarray(count._data if isinstance(count, Tensor) else count)
    mapping = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    reindexed = np.empty_like(nb)
    for i, v in enumerate(nb):
        v = int(v)
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
        reindexed[i] = mapping[v]
    dst = np.repeat(np.arange(len(xs)), ct)
    return (Tensor(jnp.asarray(reindexed), stop_gradient=True),
            Tensor(jnp.asarray(dst), stop_gradient=True),
            Tensor(jnp.asarray(np.array(out_nodes)), stop_gradient=True))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Reference: geometric/sampling/neighbors.py sample_neighbors (CSC
    graph, CPU kernel; host-side sampling like the reference)."""
    r = np.asarray(row._data if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr._data if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes._data
                       if isinstance(input_nodes, Tensor) else input_nodes)
    rng = np.random.RandomState(0)
    out, counts = [], []
    for v in nodes:
        beg, end = int(cp[int(v)]), int(cp[int(v) + 1])
        neigh = r[beg:end]
        if sample_size != -1 and len(neigh) > sample_size:
            neigh = rng.choice(neigh, size=sample_size, replace=False)
        out.append(neigh)
        counts.append(len(neigh))
    out = np.concatenate(out) if out else np.empty((0,), r.dtype)
    return (Tensor(jnp.asarray(out), stop_gradient=True),
            Tensor(jnp.asarray(np.array(counts, np.int32)),
                   stop_gradient=True))
