"""paddle.save / paddle.load — pickle-based object persistence.

Reference: python/paddle/framework/io.py:721 (save) / :960 (load). Tensors are
serialized as numpy arrays + dtype tag (bfloat16 round-trips via uint16 view);
nested dict/list state_dicts mirror paddle's format so checkpoints are portable.
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor

_BF16_TAG = "__paddle_tpu_bf16__"


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj._data)
        if arr.dtype == jnp.bfloat16:
            return {_BF16_TAG: True, "data": arr.view(np.uint16),
                    "trainable": not obj.stop_gradient}
        return {"__paddle_tpu_tensor__": True, "data": arr,
                "trainable": not obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get(_BF16_TAG):
            arr = obj["data"].view(jnp.bfloat16)
            return arr if return_numpy else Tensor(
                arr, stop_gradient=not obj.get("trainable", False))
        if obj.get("__paddle_tpu_tensor__"):
            arr = obj["data"]
            return arr if return_numpy else Tensor(
                arr, stop_gradient=not obj.get("trainable", False))
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # atomic publish: a kill mid-write must leave either the old file or
    # the new one, never a torn pickle; pickle streams straight into the
    # temp file — no full in-memory blob (lazy import: fault.py is
    # stdlib-only but lives under the heavier distributed package)
    packed = _pack(obj)
    from ..distributed.fault import atomic_write
    atomic_write(path, lambda f: pickle.dump(packed, f, protocol=protocol))


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
