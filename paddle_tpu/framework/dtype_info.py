"""paddle.finfo/iinfo + dtype predicates.

Reference: python/paddle/framework/dtype.py (finfo:109, iinfo:55 pybind
wrappers over std::numeric_limits) and tensor/attribute.py
is_complex/is_floating_point/is_integer.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dtype import convert_dtype, is_complex as _dt_is_complex
from ..core.dtype import is_floating as _dt_is_floating
from ..core.tensor import Tensor

__all__ = ["finfo", "iinfo", "is_complex", "is_floating_point",
           "is_integer"]


class finfo:
    """Reference: paddle.finfo — float type limits."""

    def __init__(self, dtype):
        jd = convert_dtype(dtype)
        info = jnp.finfo(jd)
        self.dtype = str(np.dtype(info.dtype).name) if hasattr(
            info, "dtype") else str(jd)
        self.bits = info.bits
        self.eps = float(info.eps)
        self.min = float(info.min)
        self.max = float(info.max)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(getattr(info, "resolution", info.eps))


class iinfo:
    """Reference: paddle.iinfo — integer type limits."""

    def __init__(self, dtype):
        jd = convert_dtype(dtype)
        info = jnp.iinfo(jd)
        self.dtype = str(np.dtype(info.dtype).name)
        self.bits = info.bits
        self.min = int(info.min)
        self.max = int(info.max)


def _dtype_of(x):
    return x.dtype if isinstance(x, Tensor) else convert_dtype(x)


def is_complex(x):
    """Reference: paddle.is_complex."""
    return _dt_is_complex(_dtype_of(x))


def is_floating_point(x):
    """Reference: paddle.is_floating_point."""
    return _dt_is_floating(_dtype_of(x))


def is_integer(x):
    """Reference: paddle.is_integer."""
    d = _dtype_of(x)
    return jnp.issubdtype(d, jnp.integer)
