from . import io  # noqa: F401
from ..core.random import seed  # noqa: F401
from .io import load, save  # noqa: F401
