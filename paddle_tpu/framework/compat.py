"""Small framework-level compat shims.

Reference: base/framework.py LazyGuard/_create_parameter helpers, reader
decorator paddle.batch (python/paddle/reader/decorator.py:62),
device.cuda rng-state accessors.
"""
from __future__ import annotations

__all__ = ["LazyGuard", "create_parameter", "batch", "check_shape",
           "get_cuda_rng_state", "set_cuda_rng_state"]


class LazyGuard:
    """Reference: paddle.LazyGuard — delays parameter materialisation.
    XLA arrays are cheap to create eagerly on host, so this guard is a
    transparent context (init happens immediately; semantics preserved
    because paddle code only relies on params existing after exit)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference: paddle.create_parameter (static/nn/common.py) — a free
    Parameter outside any Layer."""
    from .. import nn
    helper = nn.Layer()
    return helper.create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def batch(reader, batch_size, drop_last=False):
    """Reference: paddle.batch (reader/decorator.py:62) — group a sample
    reader into a mini-batch reader."""
    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def check_shape(shape):
    """Reference: paddle.check_shape — validate a shape argument."""
    from ..core.tensor import Tensor
    if isinstance(shape, Tensor):
        return
    for s in shape:
        if not isinstance(s, (int,)) and not isinstance(s, Tensor):
            raise TypeError(f"shape entries must be int/Tensor, got {s!r}")
        if isinstance(s, int) and s < -1:
            raise ValueError(f"invalid dim {s} in shape {shape}")


def get_cuda_rng_state():
    """Reference: paddle.get_cuda_rng_state — maps to the framework RNG
    (no CUDA on TPU deployments; state round-trips with set_)."""
    from ..core.random import get_rng_state
    return [get_rng_state()]


def set_cuda_rng_state(state_list):
    from ..core.random import set_rng_state
    if isinstance(state_list, (list, tuple)) and state_list:
        set_rng_state(state_list[0])
