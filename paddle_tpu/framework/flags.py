"""Runtime flag registry — paddle.set_flags / get_flags + FLAGS_ env layer.

Reference: paddle/phi/core/flags.cc (PHI_DEFINE_EXPORTED_* registry, ~104
flags) exported to python via paddle.set_flags (SURVEY §5 config/flag
system). TPU-native: flags map onto jax.config / XLA options where a direct
equivalent exists; unknown FLAGS_* raise like the reference's enforce.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict

__all__ = ["set_flags", "get_flags", "register_flag"]


class _Flag:
    def __init__(self, name, default, doc="", on_set: Callable | None = None):
        self.name = name
        self.value = default
        self.doc = doc
        self.on_set = on_set


_registry: Dict[str, _Flag] = {}


def register_flag(name, default, doc="", on_set=None):
    _registry[name] = _Flag(name, default, doc, on_set)


def _set_default_dtype_flag(v):
    from ..core.dtype import set_default_dtype
    set_default_dtype(v)


def _set_check_nan_inf(v):
    from ..core import dispatch
    dispatch._check_nan_inf = bool(v)


def _set_check_nan_inf_window(v):
    from ..core import dispatch
    w = int(v)
    if w < 1:
        raise ValueError("FLAGS_check_nan_inf_window must be >= 1")
    dispatch._nan_window = w


# ---- built-in flags (TPU-meaningful subset of the reference's set) ----
register_flag("FLAGS_check_nan_inf", False,
              "check every eager op output for NaN/Inf "
              "(reference: eager/nan_inf_utils.h)", _set_check_nan_inf)
register_flag("FLAGS_check_nan_inf_window", 1,
              "results batched per blocking NaN-check host sync (1 = "
              "raise at the op, the reference semantics; >1 amortizes "
              "the device sync, the error may surface up to N-1 ops late)",
              _set_check_nan_inf_window)
register_flag("FLAGS_default_dtype", "float32",
              "default floating dtype for tensor creation",
              _set_default_dtype_flag)
register_flag("FLAGS_benchmark", False, "sync after every op when timing")
register_flag("FLAGS_allocator_strategy", "auto_growth",
              "kept for API parity; XLA/PjRt owns device memory")
register_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92,
              "kept for API parity; use XLA_PYTHON_CLIENT_MEM_FRACTION")
register_flag("FLAGS_cudnn_deterministic", False,
              "XLA on TPU is deterministic by construction")
register_flag("FLAGS_embedding_deterministic", False,
              "XLA on TPU is deterministic by construction")
register_flag("FLAGS_use_autotune", True, "XLA autotuning is always on")
register_flag("FLAGS_use_flash_attention", True,
              "route scaled_dot_product_attention through the Pallas "
              "flash-attention kernel when eligible")


def set_flags(flags: dict):
    """paddle.set_flags (reference: python/paddle/base/framework.py)."""
    for name, value in flags.items():
        flag = _registry.get(name)
        if flag is None:
            raise ValueError(
                f"unknown flag {name!r}; known flags: "
                f"{sorted(_registry)}")
        flag.value = value
        if flag.on_set is not None:
            flag.on_set(value)


def get_flags(names):
    """paddle.get_flags."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for name in names:
        flag = _registry.get(name)
        if flag is None:
            raise ValueError(f"unknown flag {name!r}")
        out[name] = flag.value
    return out


def _load_env_flags():
    """FLAGS_* environment variables override defaults at import (reference:
    flags parsed at core init, pybind/pybind.cc)."""
    for name, flag in _registry.items():
        if name in os.environ:
            raw = os.environ[name]
            cur = flag.value
            if isinstance(cur, bool):
                value: Any = raw.lower() in ("1", "true", "yes")
            elif isinstance(cur, int):
                value = int(raw)
            elif isinstance(cur, float):
                value = float(raw)
            else:
                value = raw
            set_flags({name: value})


_load_env_flags()
