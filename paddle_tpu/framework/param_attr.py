"""ParamAttr — parameter property bundle (reference: python/paddle/base/param_attr.py).

Carries name/initializer/learning_rate/regularizer/trainable/need_clip through
Layer.create_parameter, exactly the role it plays in the reference's LayerHelper.
"""
from __future__ import annotations


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        """Normalize weight_attr/bias_attr arguments.

        Accepts None (defaults), False (no parameter), a ParamAttr, an
        Initializer instance, or a name string — reference semantics of
        ParamAttr._to_attr.
        """
        if arg is None:
            return ParamAttr()
        if arg is False:
            return False
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        # assume initializer-like (callable (shape, dtype) -> array)
        return ParamAttr(initializer=arg)
