"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py,
PHI kernels reshape/transpose/concat/...). Pure-metadata ops are free under XLA."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(i) for i in v.numpy())
    if isinstance(v, (int, np.integer)):
        return int(v)
    return tuple(int(i) if not isinstance(i, Tensor) else int(i.item()) for i in v)


def cast(x, dtype):
    return x.astype(dtype)


def reshape(x, shape, name=None):
    from ..core.enforce import check_reshape
    shape = _ints(shape)
    check_reshape(x.shape, shape)
    return apply("reshape", lambda a: a.reshape(shape), [x])


def reshape_(x, shape, name=None):
    return x._inplace(reshape, shape)


def transpose(x, perm, name=None):
    perm = _ints(perm)
    return apply("transpose", lambda a: jnp.transpose(a, perm), [x])


def t(x, name=None):
    if x.ndim < 2:
        return x.clone()
    return transpose(x, [1, 0])


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def f(a):
        shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return a.reshape(shape)
    return apply("flatten", f, [x])


def squeeze(x, axis=None, name=None):
    if axis is None:
        return apply("squeeze", lambda a: jnp.squeeze(a), [x])
    ax = _ints(axis)
    if isinstance(ax, int):
        ax = (ax,)
    ax = tuple(a for a in ax if x.shape[a] == 1)
    return apply("squeeze", lambda a: jnp.squeeze(a, axis=ax), [x])


def unsqueeze(x, axis, name=None):
    ax = _ints(axis)
    return apply("unsqueeze", lambda a: jnp.expand_dims(a, ax), [x])


def squeeze_(x, axis=None, name=None):
    return x._inplace(squeeze, axis)


def unsqueeze_(x, axis, name=None):
    return x._inplace(unsqueeze, axis)


def concat(x, axis=0, name=None):
    from ..core.enforce import check_concat
    tensors = list(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    check_concat([t.shape for t in tensors], ax)
    return apply("concat", lambda *xs: jnp.concatenate(xs, axis=ax), tensors)


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply("stack", lambda *xs: jnp.stack(xs, axis=axis), tensors)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [-1 if s in (-1, None) else int(s) for s in num_or_sections]
        known = sum(s for s in sizes if s >= 0)
        sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes)
    out = apply("split", lambda a: tuple(jnp.split(a, offsets[1:-1].tolist(),
                                                   axis=ax)), [x], nout=len(sizes))
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    out = apply("unbind",
                lambda a: tuple(jnp.squeeze(s, axis=axis)
                                for s in jnp.split(a, n, axis=axis)),
                [x], nout=n)
    return list(out) if isinstance(out, tuple) else [out]


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return apply("tile", lambda a: jnp.tile(a, reps), [x])


def expand(x, shape, name=None):
    shape = _ints(shape)
    tgt = tuple(x.shape[i - (len(shape) - x.ndim)] if s in (-1, None) and
                i >= len(shape) - x.ndim else s for i, s in enumerate(shape))
    return apply("expand", lambda a: jnp.broadcast_to(a, tgt), [x])


def expand_as(x, y, name=None):
    return apply("expand_as", lambda a: jnp.broadcast_to(a, tuple(y.shape)), [x])


def broadcast_to(x, shape, name=None):
    return apply("broadcast_to",
                 lambda a: jnp.broadcast_to(a, _ints(shape)), [x])


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    tgt = np.broadcast_shapes(*shapes)
    return [broadcast_to(t, tgt) for t in inputs]


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    ax = _ints(axis)
    return apply("flip", lambda a: jnp.flip(a, axis=ax), [x])


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), [x])


def roll(x, shifts, axis=None, name=None):
    sh = _ints(shifts)
    ax = _ints(axis) if axis is not None else None
    return apply("roll", lambda a: jnp.roll(a, sh, axis=ax), [x])


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis",
                 lambda a: jnp.moveaxis(a, _ints(source), _ints(destination)), [x])


def swapaxes(x, axis0, axis1, name=None):
    return apply("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), [x])


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    if idx.ndim > 1:
        idx = idx.reshape(-1)
    return apply("gather", lambda a: jnp.take(a, idx, axis=ax), [x])


def gather_nd(x, index, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a):
        ix = tuple(jnp.moveaxis(idx, -1, 0))
        return a[ix]
    return apply("gather_nd", f, [x])


def scatter(x, index, updates, overwrite=True, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx.reshape(-1)

    def f(a, u):
        if overwrite:
            return a.at[idx].set(u)
        # paddle semantics: non-overwrite zeroes target rows then accumulates
        zeroed = a.at[idx].set(jnp.zeros_like(u))
        return zeroed.at[idx].add(u)
    return apply("scatter", f, [x, updates])


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._inplace(scatter, index, updates, overwrite)


def scatter_nd_add(x, index, updates, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a, u):
        ix = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[ix].add(u)
    return apply("scatter_nd_add", f, [x, updates])


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    return scatter_nd_add(zeros(shape, dtype=updates.dtype), index, updates)


def index_select(x, index, axis=0, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    return apply("index_select", lambda a: jnp.take(a, idx, axis=axis), [x])


def index_sample(x, index, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    return apply("index_sample",
                 lambda a: jnp.take_along_axis(a, idx, axis=1), [x])


def index_add(x, index, axis, value, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a, v):
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vmoved)
        return jnp.moveaxis(out, 0, axis)
    return apply("index_add", f, [x, value])


def index_put(x, indices, value, accumulate=False, name=None):
    ix = tuple(i._data if isinstance(i, Tensor) else jnp.asarray(i)
               for i in indices)

    def f(a, v):
        return a.at[ix].add(v) if accumulate else a.at[ix].set(v)
    if isinstance(value, Tensor):
        return apply("index_put", f, [x, value])
    return apply("index_put", lambda a: f(a, value), [x])


def masked_select(x, mask, name=None):
    # Output shape is data-dependent: sync the mask to host for the index set,
    # then gather differentiably so gradients still flow to x.
    m = np.asarray(mask._data if isinstance(mask, Tensor) else mask)
    m = np.broadcast_to(m, tuple(x.shape))
    idx = jnp.asarray(np.flatnonzero(m))
    return apply("masked_select", lambda a: jnp.take(a.reshape(-1), idx), [x])


def masked_fill(x, mask, value, name=None):
    m = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    v = value.item() if isinstance(value, Tensor) and value.ndim == 0 else value
    if isinstance(v, Tensor):
        return apply("masked_fill", lambda a, b: jnp.where(m, b, a), [x, v])
    return apply("masked_fill", lambda a: jnp.where(m, v, a), [x])


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero
        return nonzero(condition, as_tuple=True)
    cond = condition._data if isinstance(condition, Tensor) else jnp.asarray(
        condition)
    if not isinstance(x, Tensor) and not isinstance(y, Tensor):
        return Tensor(jnp.where(cond, x, y))
    if not isinstance(x, Tensor):
        return apply("where", lambda b: jnp.where(cond, x, b), [y])
    if not isinstance(y, Tensor):
        return apply("where", lambda a: jnp.where(cond, a, y), [x])
    return apply("where", lambda a, b: jnp.where(cond, a, b), [x, y])


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
    return apply("take_along_axis",
                 lambda a: jnp.take_along_axis(a, idx, axis=axis), [arr])


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):  # noqa: A002
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)

    def f(a, v):
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype) \
            if not hasattr(v, "shape") or v.shape != idx.shape else v.astype(a.dtype)
        dims = list(range(a.ndim))
        dims.remove(axis % a.ndim)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        full_idx = [None] * a.ndim
        full_idx[axis % a.ndim] = idx
        for d in dims:
            full_idx[d] = grids[d]
        ix = tuple(full_idx)
        if reduce == "assign":
            return a.at[ix].set(v)
        if reduce == "add":
            return a.at[ix].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[ix].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")
    if isinstance(values, Tensor):
        return apply("put_along_axis", f, [arr, values])
    return apply("put_along_axis", lambda a: f(a, jnp.asarray(values)), [arr])


def repeat_interleave(x, repeats, axis=None, name=None):
    if axis is None:
        x = flatten(x)
        axis = 0
    if isinstance(repeats, Tensor):
        reps = repeats._data
        total = int(jnp.sum(reps))
        return apply("repeat_interleave",
                     lambda a: jnp.repeat(a, reps, axis=axis,
                                          total_repeat_length=total), [x])
    return apply("repeat_interleave",
                 lambda a: jnp.repeat(a, int(repeats), axis=axis), [x])


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    res = np.unique(np.asarray(x._data), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(res)
    out = [Tensor(r) for r in res]
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
        change = np.concatenate([[True], arr[1:] != arr[:-1]])
    else:
        moved = np.moveaxis(arr, axis, 0)
        change = np.concatenate(
            [[True], np.any(moved[1:] != moved[:-1],
                            axis=tuple(range(1, moved.ndim)))])
    idx = np.nonzero(change)[0]
    vals = np.take(arr, idx, axis=axis or 0)
    outs = [Tensor(vals)]
    if return_inverse:
        outs.append(Tensor(np.cumsum(change) - 1))
    if return_counts:
        counts = np.diff(np.append(idx, arr.shape[axis or 0]))
        outs.append(Tensor(counts))
    return outs[0] if len(outs) == 1 else tuple(outs)


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    idx = [np.s_[:]] * x.ndim
    for ax, s, e in zip(_ints(axes) if not isinstance(axes, int) else [axes],
                        _ints(starts) if not isinstance(starts, int) else [starts],
                        _ints(ends) if not isinstance(ends, int) else [ends]):
        idx[ax] = np.s_[s:e]
    idx = tuple(idx)
    return apply("slice", lambda a: a[idx], [x])


def strided_slice(x, axes, starts, ends, strides, name=None):
    idx = [np.s_[:]] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = np.s_[s:e:st]
    idx = tuple(idx)
    return apply("strided_slice", lambda a: a[idx], [x])


def crop(x, shape=None, offsets=None, name=None):
    shape = _ints(shape)
    offsets = _ints(offsets) if offsets is not None else (0,) * x.ndim
    idx = tuple(np.s_[o:o + s if s != -1 else None]
                for o, s in zip(offsets, shape))
    return apply("crop", lambda a: a[idx], [x])


def as_complex(x, name=None):
    return apply("as_complex", lambda a: a[..., 0] + 1j * a[..., 1], [x])


def as_real(x, name=None):
    return apply("as_real",
                 lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), [x])


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return Tensor(x._data.view(convert_dtype(shape_or_dtype)))


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def atleast_1d(*inputs, name=None):
    outs = [reshape(x, [1]) if x.ndim == 0 else x for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for x in inputs:
        while x.ndim < 2:
            x = unsqueeze(x, 0)
        outs.append(x)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for x in inputs:
        while x.ndim < 3:
            x = unsqueeze(x, -1) if x.ndim >= 1 else unsqueeze(x, 0)
        outs.append(x)
    return outs[0] if len(outs) == 1 else outs


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), [x, y])


def tolist(x):
    return x.tolist()


def unstack(x, axis=0, num=None, name=None):
    """Reference: python/paddle/tensor/manipulation.py unstack — split along
    `axis` into a list of tensors with that axis removed."""
    axis = axis % x.ndim
    n = x.shape[axis] if num is None else num
    outs = apply("unstack",
                 lambda a: tuple(jnp.squeeze(s, axis) for s in
                                 jnp.split(a, n, axis=axis)),
                 [x], nout=n)
    return list(outs) if isinstance(outs, tuple) else [outs]


def as_strided(x, shape, stride, offset=0, name=None):
    """Reference: python/paddle/tensor/manipulation.py as_strided — view
    with explicit strides, realised as a gather of the flat buffer (XLA has
    no aliasing views; numerics match)."""
    import numpy as _np
    grids = _np.indices(tuple(int(s) for s in shape))
    flat_idx = offset + sum(g * int(st) for g, st in zip(grids, stride))
    idx = jnp.asarray(flat_idx.reshape(-1), jnp.int32)
    return apply("as_strided",
                 lambda a: jnp.take(a.reshape(-1), idx).reshape(
                     tuple(int(s) for s in shape)), [x])


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """Reference: python/paddle/tensor/linalg.py histogramdd. Returns
    (hist, list_of_edges)."""
    import numpy as _np
    xs = _np.asarray(x._data if isinstance(x, Tensor) else x)
    ws = None if weights is None else _np.asarray(
        weights._data if isinstance(weights, Tensor) else weights)
    hist, edges = _np.histogramdd(xs, bins=bins, range=ranges,
                                  density=density, weights=ws)
    from ..core.tensor import Tensor as _T2
    return (_T2(jnp.asarray(hist), stop_gradient=True),
            [_T2(jnp.asarray(e), stop_gradient=True) for e in edges])
