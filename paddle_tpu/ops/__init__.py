"""Op registry + Tensor method binding.

This is the TPU-native stand-in for the reference's codegen spine
(paddle/phi/api/yaml/ops.yaml → generated C++ API + pybind methods, SURVEY §1):
ops are plain python functions over jnp (VJPs come free from jax.vjp at dispatch
time), and this module binds them onto both the ``paddle_tpu`` namespace and
``Tensor`` methods — the equivalent of eager_op_function.cc + math op patches
(python/paddle/base/dygraph/math_op_patch.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .creation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .generated_root import *  # noqa: F401,F403  (codegen spine, ops.yaml)
from .inplace import *  # noqa: F401,F403  (op_ in-place family)

from . import creation, linalg, logic, manipulation, math, random_ops, search
from . import generated_root, inplace


def einsum(equation, *operands, name=None):
    tensors = list(operands)
    return apply("einsum", lambda *xs: jnp.einsum(equation, *xs), tensors)


def one_hot(x, num_classes, name=None):
    import jax
    return Tensor(jax.nn.one_hot(x._data, num_classes))


# Bind op functions as Tensor methods (the reference patches these via pybind
# eager_method.cc + tensor_patch_methods.py).
_METHOD_SOURCES = [math, manipulation, logic, linalg, search, creation,
                   generated_root, inplace]
_NO_METHOD = {
    "to_tensor", "zeros", "ones", "full", "arange", "linspace", "logspace",
    "eye", "empty", "meshgrid", "tril_indices", "triu_indices", "assign",
    "broadcast_tensors", "broadcast_shape", "is_tensor", "scatter_nd",
    "complex", "polar",
}


def _bind():
    import types
    for mod in _METHOD_SOURCES:
        for name, fn in vars(mod).items():
            if name.startswith("_") or not isinstance(fn, types.FunctionType):
                continue
            if fn.__module__ != mod.__name__:  # skip imported helpers
                continue
            if name in _NO_METHOD or hasattr(Tensor, name):
                continue
            setattr(Tensor, name, fn)
    # in-place aliases + a few extras paddle exposes as methods
    Tensor.add_ = lambda self, y: self._inplace(add, y)
    Tensor.subtract_ = lambda self, y: self._inplace(subtract, y)
    Tensor.multiply_ = lambda self, y: self._inplace(multiply, y)
    Tensor.divide_ = lambda self, y: self._inplace(divide, y)
    Tensor.clip_ = lambda self, min=None, max=None: self._inplace(clip, min, max)
    Tensor.scale_ = lambda self, s=1.0, bias=0.0, bias_after_scale=True: \
        self._inplace(scale, s, bias, bias_after_scale)
    Tensor.exp_ = lambda self: self._inplace(exp)
    Tensor.sqrt_ = lambda self: self._inplace(sqrt)
    Tensor.tanh_ = lambda self: self._inplace(tanh)
    Tensor.floor_ = lambda self: self._inplace(floor)
    Tensor.ceil_ = lambda self: self._inplace(ceil)
    Tensor.round_ = lambda self: self._inplace(round)
    Tensor.neg_ = lambda self: self._inplace(neg)
    Tensor.reciprocal_ = lambda self: self._inplace(reciprocal)

    # remaining reference tensor_method_func names (round 4): bind
    # namespace functions that live outside the method-source modules
    def _late_bind():
        import paddle_tpu as _p
        from .inplace import addmm_, index_put_, masked_scatter_  # noqa
        Tensor.addmm_ = addmm_
        Tensor.put_along_axis_ = lambda self, *a, **k: self._inplace(
            _p.put_along_axis, *a, **k)
        Tensor.masked_scatter_ = masked_scatter_
        Tensor.stft = lambda self, *a, **k: _p.signal.stft(self, *a, **k)
        Tensor.istft = lambda self, *a, **k: _p.signal.istft(self, *a, **k)
        Tensor.lu = lambda self, *a, **k: _p.linalg.lu(self, *a, **k)
        Tensor.lu_unpack = lambda self, *a, **k: _p.linalg.lu_unpack(
            self, *a, **k)
        Tensor.cond = lambda self, p=None: _p.linalg.cond(self, p)
        Tensor.householder_product =             lambda self, tau: _p.linalg.householder_product(self, tau)
        Tensor.multinomial = lambda self, *a, **k: _p.multinomial(
            self, *a, **k)
        Tensor.is_complex = lambda self: _p.is_complex(self)
        Tensor.is_floating_point = lambda self: _p.is_floating_point(self)
        Tensor.is_integer = lambda self: _p.is_integer(self)
        Tensor.__xor__ = lambda self, o: self.bitwise_xor(o)             if not str(self.dtype).startswith("bool") else             self.logical_xor(o)
        Tensor.__rxor__ = Tensor.__xor__
        Tensor.top_p_sampling = lambda self, *a, **k: _p.top_p_sampling(
            self, *a, **k)
        Tensor.pca_lowrank = lambda self, *a, **k: _p.linalg.pca_lowrank(
            self, *a, **k)

    Tensor._late_bind = staticmethod(_late_bind)


_bind()

# drop the submodule name so `from paddle_tpu.ops import *` does not shadow
# the paddle_tpu.linalg NAMESPACE module with this implementation module
# (the object stays reachable via _METHOD_SOURCES and sys.modules)
del linalg
