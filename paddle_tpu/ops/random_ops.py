"""Random sampling ops (reference: python/paddle/tensor/random.py, phi Generator).
All draw keys from the counter-based global generator (core/random.py), so results
are deterministic under paddle.seed and stay functional under jit tracing."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as prandom
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def rand(shape, dtype=None, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(prandom.next_key(), _shape(shape), dt))


def randn(shape, dtype=None, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.normal(prandom.next_key(), _shape(shape), dt))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ()))
        return Tensor(m + s * jax.random.normal(prandom.next_key(), shp,
                                                get_default_dtype()))
    shp = _shape(shape) if shape is not None else ()
    return Tensor(mean + std * jax.random.normal(prandom.next_key(), shp,
                                                 get_default_dtype()))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = mean + std * jax.random.normal(prandom.next_key(),
                                             tuple(x.shape), x.dtype)
    return x


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(prandom.next_key(), _shape(shape), dt,
                                     minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    x._data = jax.random.uniform(prandom.next_key(), tuple(x.shape), x.dtype,
                                 minval=min, maxval=max)
    return x


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(prandom.next_key(), _shape(shape), low, high,
                                     convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.randint(prandom.next_key(), tuple(x.shape), low,
                                     high, dt))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(prandom.next_key(), n)
                  .astype(convert_dtype(dtype)))


def bernoulli(x, name=None):
    return Tensor(jax.random.bernoulli(prandom.next_key(),
                                       x._data).astype(x.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(prandom.next_key(), p,
                                   tuple(x.shape)).astype(x.dtype)
    return x


def multinomial(x, num_samples=1, replacement=False, name=None):
    probs = x._data / jnp.sum(x._data, axis=-1, keepdims=True)
    logits = jnp.log(probs)
    if replacement:
        out = jax.random.categorical(prandom.next_key(), logits,
                                     shape=(*logits.shape[:-1], num_samples)
                                     if logits.ndim > 1 else (num_samples,),
                                     axis=-1)
    else:
        k = prandom.next_key()
        g = jax.random.gumbel(k, logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(prandom.next_key(), x._data)
                  .astype(x.dtype))


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(prandom.next_key(), tuple(x.shape),
                                      x.dtype) / lam)
    return x


def rand_like(x, dtype=None, name=None):
    dt = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.uniform(prandom.next_key(), tuple(x.shape), dt))


def randn_like(x, dtype=None, name=None):
    dt = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.normal(prandom.next_key(), tuple(x.shape), dt))


def binomial(count, prob, name=None):
    """Reference: python/paddle/tensor/random.py binomial — sample
    Binomial(count, prob) elementwise."""
    import jax as _jax
    c = count._data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._data if isinstance(prob, Tensor) else jnp.asarray(prob)
    shape = jnp.broadcast_shapes(jnp.shape(c), jnp.shape(p))
    out = _jax.random.binomial(prandom.next_key(),
                               jnp.broadcast_to(c, shape).astype(jnp.float32),
                               jnp.broadcast_to(p, shape).astype(jnp.float32))
    return Tensor(out.astype(jnp.int64), stop_gradient=True)


def standard_gamma(alpha, name=None):
    """Reference: python/paddle/tensor/random.py standard_gamma."""
    import jax as _jax
    a = alpha._data if isinstance(alpha, Tensor) else jnp.asarray(alpha)
    out = _jax.random.gamma(prandom.next_key(), a.astype(jnp.float32))
    return Tensor(out, stop_gradient=True)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Reference: python/paddle/tensor/random.py top_p_sampling (phi
    top_p_sampling_kernel): nucleus sampling — keep the smallest prefix of
    the descending-sorted distribution whose mass exceeds ps, renormalize,
    sample. x: [B, V] probabilities; ps: [B] cutoffs. Returns
    (scores, ids) like the reference."""
    import jax
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    pa = ps._data if isinstance(ps, Tensor) else jnp.asarray(ps)
    key = prandom.next_key()

    order = jnp.argsort(-xa, axis=-1)
    sorted_p = jnp.take_along_axis(xa, order, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    # keep tokens while the mass BEFORE them is < ps (always >= 1 token)
    keep = (cum - sorted_p) < pa[..., None]
    masked = jnp.where(keep, sorted_p, 0.0)
    norm = masked / jnp.maximum(masked.sum(-1, keepdims=True), 1e-12)
    idx_in_sorted = jax.random.categorical(
        key, jnp.log(jnp.maximum(norm, 1e-12)), axis=-1)
    ids = jnp.take_along_axis(order, idx_in_sorted[..., None],
                              axis=-1)
    scores = jnp.take_along_axis(xa, ids, axis=-1)
    return (Tensor(scores, stop_gradient=True),
            Tensor(ids.astype(jnp.int64), stop_gradient=True))
