"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def _cmp(name, jfn):
    def op(x, y, name=None):
        a = x._data if isinstance(x, Tensor) else x
        b = y._data if isinstance(y, Tensor) else y
        return Tensor(jfn(a, b))
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)


def logical_not(x, name=None):
    return Tensor(jnp.logical_not(x._data))


def bitwise_not(x, name=None):
    return Tensor(jnp.bitwise_not(x._data))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(x._data, y._data, rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(x._data, y._data, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


def equal_all(x, y, name=None):
    if tuple(x.shape) != tuple(y.shape):
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.all(x._data == y._data))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
