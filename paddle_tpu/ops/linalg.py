"""Linear algebra ops (reference: python/paddle/tensor/linalg.py — matmul at
linalg.py:151; PHI blas via funcs/blas). matmul maps straight to the MXU through
XLA dot_general; bf16 inputs hit the systolic array natively."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Reference: python/paddle/tensor/linalg.py:151 → _C_ops.matmul."""
    from ..core.enforce import check_matmul
    check_matmul(x.shape, y.shape if hasattr(y, "shape") else
                 list(jnp.shape(y)), transpose_x, transpose_y)

    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply("matmul", f, [x, y])


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply("bmm", jnp.matmul, [x, y])


def mv(x, vec, name=None):
    return apply("mv", jnp.matmul, [x, vec])


def multi_dot(tensors, name=None):
    return apply("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs), list(tensors))


def norm(x, p=None, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    pp = p if p is not None else ("fro" if (ax is None or
                                            isinstance(ax, tuple)) else 2)

    def f(a):
        if ax is None:
            flat = a.reshape(-1)
            if pp == "fro" or pp == 2:
                return jnp.sqrt(jnp.sum(flat * flat))
            if pp == np.inf:
                return jnp.max(jnp.abs(flat))
            if pp == -np.inf:
                return jnp.min(jnp.abs(flat))
            if pp == 1:
                return jnp.sum(jnp.abs(flat))
            if pp == 0:
                return jnp.sum((flat != 0).astype(a.dtype))
            return jnp.sum(jnp.abs(flat) ** pp) ** (1.0 / pp)
        return jnp.linalg.norm(a, ord=pp, axis=ax, keepdims=keepdim)
    return apply("norm", f, [x])


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("vector_norm",
                 lambda a: jnp.linalg.vector_norm(a, ord=p, axis=ax,
                                                  keepdims=keepdim), [x])


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    ax = tuple(int(a) for a in axis)

    def f(a):
        moved = jnp.moveaxis(a, ax, (-2, -1))
        out = jnp.linalg.matrix_norm(moved, ord=p, keepdims=keepdim)
        if keepdim:
            out = jnp.moveaxis(out, (-2, -1), ax)
        return out
    return apply("matrix_norm", f, [x])


def dist(x, y, p=2, name=None):
    def f(a, b):
        return jnp.linalg.norm((a - b).reshape(-1), ord=p)
    return apply("dist", f, [x, y])


def cholesky(x, upper=False, name=None):
    def f(a):
        low = jnp.linalg.cholesky(a)
        return jnp.swapaxes(low, -1, -2) if upper else low
    return apply("cholesky", f, [x])


def inverse(x, name=None):
    return apply("inverse", jnp.linalg.inv, [x])


inv = inverse


def det(x, name=None):
    return apply("det", jnp.linalg.det, [x])


def slogdet(x, name=None):
    out = apply("slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), [x], nout=2)
    return out


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                                   hermitian=hermitian), [x])


def solve(x, y, name=None):
    return apply("solve", jnp.linalg.solve, [x, y])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    from jax.scipy.linalg import solve_triangular

    def f(a, b):
        return solve_triangular(a, b, lower=not upper,
                                trans=1 if transpose else 0,
                                unit_diagonal=unitriangular)
    return apply("triangular_solve", f, [x, y])


def cholesky_solve(x, y, upper=False, name=None):
    from jax.scipy.linalg import cho_solve

    def f(b, L):
        return cho_solve((L, not upper), b)
    return apply("cholesky_solve", f, [x, y])


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = np.linalg.lstsq(np.asarray(x._data),
                                         np.asarray(y._data), rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv))


def matrix_power(x, n, name=None):
    return apply("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), [x])


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(x._data, rtol=tol))


def qr(x, mode="reduced", name=None):
    out = apply("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), [x], nout=2)
    return out


def svd(x, full_matrices=False, name=None):
    return apply("svd",
                 lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
                 [x], nout=3)


def eig(x, name=None):
    w, v = np.linalg.eig(np.asarray(x._data))
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    return apply("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)),
                 [x], nout=2)


def eigvals(x, name=None):
    return Tensor(np.linalg.eigvals(np.asarray(x._data)))


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), [x])


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply("cov", lambda a: jnp.cov(a, rowvar=rowvar,
                                          ddof=1 if ddof else 0), [x])


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), [x])


def histogram(x, bins=100, min=0, max=0, name=None):  # noqa: A002
    rng = None if (min == 0 and max == 0) else (min, max)
    hist, _ = jnp.histogram(x._data, bins=bins, range=rng)
    return Tensor(hist.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    w = weights._data if isinstance(weights, Tensor) else weights
    return Tensor(jnp.bincount(x._data, weights=w, minlength=minlength,
                               length=None))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Reference: python/paddle/tensor/linalg.py pca_lowrank (randomized
    PCA). Exact thin-SVD formulation (XLA SVD is fast at these ranks):
    returns (U, S, V) of the (optionally centered) matrix, truncated to
    q components."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    m, n = arr.shape[-2], arr.shape[-1]
    if q is None:
        q = min(6, m, n)

    def fwd(a):
        af = a.astype(jnp.float32)
        if center:
            af = af - af.mean(axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(af, full_matrices=False)
        return (u[..., :q], s[..., :q],
                jnp.swapaxes(vt, -1, -2)[..., :q])

    return apply("pca_lowrank", fwd, [x], nout=3)
