"""Elementwise + reduction math ops (reference: python/paddle/tensor/math.py,
PHI kernels under paddle/phi/kernels/{cpu,gpu}/). Each op is one jnp call through
the autograd tape; XLA fuses chains of these into single kernels, which replaces
the reference's hand-fused elementwise CUDA kernels."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy import special as jsp

from ..core.dispatch import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


def _binary(opname, jfn):
    def op(x, y, name=None):
        if not isinstance(x, Tensor) and not isinstance(y, Tensor):
            x = Tensor(x)
        return apply(opname, jfn, [x, y])
    op.__name__ = opname
    return op


def _unary(opname, jfn):
    def op(x, name=None):
        return apply(opname, jfn, [x])
    op.__name__ = opname
    return op


# ---- binary elementwise ----
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
remainder = _binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = _binary("pow", jnp.power)  # noqa: A001
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
logaddexp = _binary("logaddexp", jnp.logaddexp)
hypot = _binary("hypot", jnp.hypot)
nextafter = _binary("nextafter", jnp.nextafter)
copysign = _binary("copysign", jnp.copysign)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
heaviside = _binary("heaviside", jnp.heaviside)
inner = _binary("inner", jnp.inner)
outer = _binary("outer", jnp.outer)
kron = _binary("kron", jnp.kron)

# ---- unary elementwise: migrated to the codegen spine (ops.yaml ->
# generated_root.py; see gen.py) ----


def isnan(x, name=None):
    return apply("isnan", jnp.isnan, [x.detach() if not x.stop_gradient else x])


def isinf(x, name=None):
    return apply("isinf", jnp.isinf, [x.detach() if not x.stop_gradient else x])


def isfinite(x, name=None):
    return apply("isfinite", jnp.isfinite,
                 [x.detach() if not x.stop_gradient else x])


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num",
                 lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                          neginf=neginf), [x])


def clip(x, min=None, max=None, name=None):  # noqa: A002
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return apply("clip", lambda a: jnp.clip(a, mn, mx), [x])


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply("lerp", lambda a, b, w: a + w * (b - a), [x, y, weight])
    return apply("lerp", lambda a, b: a + weight * (b - a), [x, y])


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale
    if bias_after_scale:
        return apply("scale", lambda a: a * s + bias, [x])
    return apply("scale", lambda a: (a + bias) * s, [x])


def increment(x, value=1.0, name=None):
    return x._inplace(lambda t: apply("increment", lambda a: a + value, [t]))


def multiplex(inputs, index, name=None):
    arrs = [t for t in inputs]

    def f(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0)[0]
    return apply("multiplex", lambda *xs: f(index._data.reshape(-1), *xs), arrs)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), [x])


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return apply("addmm", lambda i, a, b: beta * i + alpha * (a @ b),
                 [input, x, y])


# ---- reductions (reference: phi/kernels reduce_*; python/paddle/tensor/math.py) ----
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    ax, dt = _axis(axis), convert_dtype(dtype)
    return apply("sum", lambda a: jnp.sum(a, axis=ax, dtype=dt,
                                          keepdims=keepdim), [x])


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("mean", lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), [x])


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax, dt = _axis(axis), convert_dtype(dtype)
    return apply("prod", lambda a: jnp.prod(a, axis=ax, dtype=dt,
                                            keepdims=keepdim), [x])


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    ax = _axis(axis)
    return apply("max", lambda a: jnp.max(a, axis=ax, keepdims=keepdim), [x])


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    ax = _axis(axis)
    return apply("min", lambda a: jnp.min(a, axis=ax, keepdims=keepdim), [x])


amax = max
amin = min


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return Tensor(jnp.all(x._data, axis=_axis(axis), keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return Tensor(jnp.any(x._data, axis=_axis(axis), keepdims=keepdim))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmax(x._data, axis=_axis(axis), keepdims=keepdim)
    return Tensor(out.astype(convert_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmin(x._data, axis=_axis(axis), keepdims=keepdim)
    return Tensor(out.astype(convert_dtype(dtype)))


def cumsum(x, axis=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    if axis is None:
        return apply("cumsum", lambda a: jnp.cumsum(a.reshape(-1), dtype=dt), [x])
    return apply("cumsum", lambda a: jnp.cumsum(a, axis=int(axis), dtype=dt), [x])


def cumprod(x, dim=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    return apply("cumprod", lambda a: jnp.cumprod(a, axis=dim, dtype=dt), [x])


def _cum_extreme(x, axis, dtype, combine, name):
    if axis is None:
        x = apply("flatten", lambda a: a.reshape(-1), [x])
        axis = 0
    ax = int(axis)

    def f(a):
        vals = lax.associative_scan(combine, a, axis=ax)
        pos = jnp.arange(a.shape[ax]).reshape(
            [-1 if i == (ax % a.ndim) else 1 for i in range(a.ndim)])
        is_new = a == vals
        inds = lax.associative_scan(
            jnp.maximum, jnp.where(is_new, pos, -1), axis=ax)
        return vals, [inds.astype(convert_dtype(dtype))]
    return apply(name, f, [x], has_aux=True)


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, jnp.maximum, "cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, jnp.minimum, "cummin")


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("logsumexp",
                 lambda a: jsp.logsumexp(a, axis=ax, keepdims=keepdim), [x])


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axis(axis)
    return apply("median", lambda a: jnp.median(a, axis=ax, keepdims=keepdim), [x])


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("nanmedian",
                 lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), [x])


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("nanmean", lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), [x])


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax, dt = _axis(axis), convert_dtype(dtype)
    return apply("nansum", lambda a: jnp.nansum(a, axis=ax, dtype=dt,
                                                keepdims=keepdim), [x])


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("var", lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0,
                                          keepdims=keepdim), [x])


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("std", lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0,
                                          keepdims=keepdim), [x])


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.count_nonzero(x._data, axis=_axis(axis), keepdims=keepdim)
                  .astype(jnp.int64))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                              axis2=axis2), [x])


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                                    axis2=axis2), [x])


def dot(x, y, name=None):
    def f(a, b):
        return jnp.sum(a * b, axis=-1)
    return apply("dot", f, [x, y])


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else (
        next(i for i, s in enumerate(x.shape) if s == 3))
    return apply("cross", lambda a, b: jnp.cross(a, b, axis=ax), [x, y])
