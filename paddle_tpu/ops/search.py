"""Search/sort ops (reference: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis, stable=stable or descending)
        return jnp.flip(out, axis=axis) if descending else out
    return apply("sort", f, [x])


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    out = jnp.argsort(x._data, axis=axis, stable=stable or descending)
    if descending:
        out = jnp.flip(out, axis=axis)
    return Tensor(out.astype(jnp.int64))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):  # noqa: A002
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)
    ax = -1 if axis is None else axis

    def f(a):
        arr = jnp.moveaxis(a, ax, -1) if ax not in (-1, a.ndim - 1) else a
        if largest:
            vals, idx = _jax_topk(arr, kk)
        else:
            vals, idx = _jax_topk(-arr, kk)
            vals = -vals
        if ax not in (-1, a.ndim - 1):
            vals = jnp.moveaxis(vals, -1, ax)
            idx = jnp.moveaxis(idx, -1, ax)
        return vals, [idx.astype(jnp.int64)]
    return apply("topk", f, [x], has_aux=True)


def _jax_topk(a, k):
    import jax.lax as lax
    return lax.top_k(a, k)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        srt = jnp.sort(a, axis=axis)
        idxs = jnp.argsort(a, axis=axis)
        vals = jnp.take(srt, k - 1, axis=axis)
        idx = jnp.take(idxs, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, [idx.astype(jnp.int64)]
    return apply("kthvalue", f, [x], has_aux=True)


def mode(x, axis=-1, keepdim=False, name=None):
    arr = np.asarray(x._data)
    moved = np.moveaxis(arr, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    shape = moved.shape[:-1]
    vals = vals.reshape(shape)
    idxs = idxs.reshape(shape)
    if keepdim:
        vals = np.expand_dims(vals, axis)
        idxs = np.expand_dims(idxs, axis)
    return Tensor(vals), Tensor(idxs)


def nonzero(x, as_tuple=False, name=None):
    arr = np.asarray(x._data)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(n.astype(np.int64)) for n in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence._data, values._data, side=side)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence._data, x._data, side=side)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def index_fill(x, index, axis, value, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[idx].set(value)
        return jnp.moveaxis(out, 0, axis)
    return apply("index_fill", f, [x])
