"""Tensor creation ops (reference: python/paddle/tensor/creation.py, PHI kernels
``full``, ``arange`` etc.). All constructors produce Tensors on the current device
via jnp; XLA handles placement."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, to_tensor  # noqa: F401

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like", "full_like",
    "arange", "linspace", "logspace", "eye", "empty", "empty_like", "diag",
    "diagflat", "meshgrid", "tril", "triu", "assign", "clone", "numel",
    "tril_indices", "triu_indices", "diag_embed", "complex", "polar",
]


def _dt(dtype, default_float=True):
    if dtype is None:
        return get_default_dtype() if default_float else None
    return convert_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(x._data, dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(x._data, dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(x._data, fill_value, dtype=convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=convert_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    from ..core.dispatch import apply
    if x.ndim == 1 and padding_value != 0:
        def f(a):
            n = a.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, a.dtype)
            return out + jnp.diag(a, k=offset) - jnp.diag(
                jnp.full((a.shape[0],), padding_value, a.dtype), k=offset)
        return apply("diag", f, [x])
    return apply("diag", lambda a: jnp.diag(a, k=offset), [x])


def diagflat(x, offset=0, name=None):
    from ..core.dispatch import apply
    return apply("diagflat", lambda a: jnp.diagflat(a, k=offset), [x])


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    from ..core.dispatch import apply

    def f(a):
        m = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (m, m), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(a)
        if (dim1, dim2) not in ((-2, -1), (a.ndim - 1, a.ndim)):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return apply("diag_embed", f, [x])


def meshgrid(*args, **kwargs):
    arrs = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[a._data for a in arrs], indexing="ij")
    return [Tensor(o) for o in outs]


def tril(x, diagonal=0, name=None):
    from ..core.dispatch import apply
    return apply("tril", lambda a: jnp.tril(a, k=diagonal), [x])


def triu(x, diagonal=0, name=None):
    from ..core.dispatch import apply
    return apply("triu", lambda a: jnp.triu(a, k=diagonal), [x])


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def assign(x, output=None):
    val = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if output is not None:
        output.set_value(val)
        return output
    return Tensor(val)


def clone(x, name=None):
    return x.clone()


def numel(x, name=None):
    return x.numel()


def complex(real, imag, name=None):  # noqa: A001
    from ..core.dispatch import apply
    return apply("complex", lambda r, i: r + 1j * i, [real, imag])


def polar(abs_, angle, name=None):
    from ..core.dispatch import apply
    return apply("polar", lambda a, t: a * jnp.exp(1j * t), [abs_, angle])
