"""In-place op variants (``op_`` family).

Reference: python/paddle/tensor/*.py — every ``foo_`` calls the inplace
ad_func (`_C_ops.foo_`). TPU-native: XLA arrays are immutable, so inplace
IS adopt-the-result (``Tensor._inplace``): the tensor object takes over the
out-of-place result's value and grad history; leaf-with-grad raises, same
as the reference eager engine.

The wrappers are generated from the registry below — one line per op keeps
the family auditable and the surface complete (~60 reference names).
"""
from __future__ import annotations

import sys

import jax.numpy as jnp

from ..core.tensor import Tensor

# base-name registry: every entry emits `<name>_` delegating to the
# out-of-place op resolved lazily from the paddle_tpu namespace (so
# generated + handwritten ops are all reachable)
_UNARY = """abs acos acosh asin asinh atan atanh ceil cos cosh digamma erf
erfinv exp expm1 floor frac i0 lgamma log log10 log1p log2 logit neg
polygamma reciprocal round rsqrt sigmoid sin sinh sqrt square tan tanh
trunc nan_to_num sgn""".split()

_BINARY = """add subtract multiply divide pow remainder mod floor_divide
floor_mod gcd lcm hypot ldexp logical_and logical_or logical_xor
bitwise_and bitwise_or bitwise_xor equal not_equal greater_equal
greater_than less_equal less_than fmax fmin maximum minimum
heaviside copysign nextafter""".split()

_OTHER = """clip scale cast cumsum cumprod tril triu transpose t squeeze
unsqueeze flatten index_add index_fill index_put addmm
masked_scatter put_along_axis
masked_fill renorm multigammaln lerp logical_not bitwise_not""".split()

__all__ = []


def _resolve(name):
    import paddle_tpu as _p
    fn = getattr(_p, name, None)
    if fn is None:
        raise NotImplementedError(
            f"in-place variant '{name}_' has no out-of-place base "
            f"'paddle.{name}'")
    return fn


def _make(name):
    def op_(x, *args, **kwargs):
        return x._inplace(_resolve(name), *args, **kwargs)
    op_.__name__ = name + "_"
    op_.__qualname__ = name + "_"
    op_.__doc__ = (f"In-place variant of paddle.{name} (reference: "
                   f"python/paddle/tensor {name}_); adopts the "
                   "out-of-place result, leaf-with-grad raises.")
    return op_


_mod = sys.modules[__name__]
for _base in _UNARY + _BINARY + _OTHER:
    _n = _base + "_"
    if not hasattr(_mod, _n):
        setattr(_mod, _n, _make(_base))
        __all__.append(_n)


def cauchy_(x, loc=0.0, scale=1.0, name=None):
    """Reference: python/paddle/tensor/random.py cauchy_ — fill with
    Cauchy(loc, scale) samples (no out-of-place counterpart)."""
    from ..core import random as _random
    import jax

    def fill(a):
        u = jax.random.uniform(_random.next_key(), a.shape,
                               minval=1e-7, maxval=1.0 - 1e-7)
        return (loc + scale * jnp.tan(jnp.pi * (u - 0.5))).astype(a.dtype)

    return x._inplace(lambda t: Tensor(fill(t._data), stop_gradient=True))


def geometric_(x, probs, name=None):
    """Reference: python/paddle/tensor/random.py geometric_ — fill with
    Geometric(probs) samples."""
    from ..core import random as _random
    import jax

    def fill(a):
        u = jax.random.uniform(_random.next_key(), a.shape,
                               minval=1e-7, maxval=1.0 - 1e-7)
        return jnp.ceil(jnp.log(u) / jnp.log1p(-probs)).astype(a.dtype)

    return x._inplace(lambda t: Tensor(fill(t._data), stop_gradient=True))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    """Reference: random.py uniform_ — refill with U(min, max)."""
    from ..core import random as _random
    import jax

    def fill(a):
        return jax.random.uniform(
            _random.next_key(), a.shape, minval=min, maxval=max
        ).astype(a.dtype)

    return x._inplace(lambda t: Tensor(fill(t._data), stop_gradient=True))


def normal_(x, mean=0.0, std=1.0, name=None):
    """Reference: random.py normal_ — refill with N(mean, std)."""
    from ..core import random as _random
    import jax

    def fill(a):
        return (mean + std * jax.random.normal(
            _random.next_key(), a.shape)).astype(a.dtype)

    return x._inplace(lambda t: Tensor(fill(t._data), stop_gradient=True))


def exponential_(x, lam=1.0, name=None):
    """Reference: random.py exponential_ (re-exported from random_ops)."""
    from .random_ops import exponential_ as _e
    return _e(x, lam)


def zero_(x, name=None):
    """Reference: tensor.py zero_ — fill with zeros."""
    return x._inplace(lambda t: t * 0)


def where_(condition, x, y, name=None):
    """Reference: where_ — in-place select on x."""
    import paddle_tpu as _p
    return x._inplace(lambda t: _p.where(condition, t, y))


__all__ += ["cauchy_", "geometric_", "uniform_", "normal_",
            "exponential_", "zero_", "where_"]
