"""Flash attention — Pallas TPU kernel (forward + backward).

Reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu (FlashAttention-2 CUDA
kernels, dynloaded from third_party/flashattn). TPU-native rebuild: online-
softmax tiling in VMEM with the MXU doing the block matmuls; the backward
recomputes P blockwise from the saved logsumexp (FA-2 style) instead of
storing the S×S matrix — O(S) memory for any sequence length.

Layout: [B, H, S, D] inside the kernels (the functional layer transposes from
paddle's [B, S, H, D]). D ≤ 128; S must divide by the block size (the
functional layer pads).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
# np.float32 constants: paddle_tpu enables jax_enable_x64, and a bare python
# float inside the kernel materializes as an f64 constant that Mosaic cannot
# legalize (tpu.truncf f64->f32).
NEG_INF = np.float32(-1e30)


def _no_x64():
    """Mosaic cannot legalize the i64 index arithmetic jax_enable_x64
    produces (even a trivial kernel fails func.return legalization), so every
    pallas_call traces under an x64-disabled scope. Inputs/outputs are
    explicit f32/bf16 arrays, so results are unaffected."""
    return jax.enable_x64(False)
# Mosaic requires the minor (lane) dim of every VMEM block to be 128-aligned
# or equal to the array dim, so per-row stats (m/l/lse/delta) are carried
# replicated across 128 lanes (same convention as
# jax/experimental/pallas/ops/tpu/flash_attention.py MIN_BLOCK_SIZE).
LANES = 128


def _rows(x, block_q):
    """Broadcast a [BQ] row-stat to the lane-replicated [BQ, LANES] layout."""
    return jax.lax.broadcast_in_dim(x, (block_q, LANES), (0,))


# ---------------- forward ----------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, kv_len):
    i = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)          # [BQ, D]
        k = k_ref[0].astype(jnp.float32)          # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal or kv_len % block_k:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = kpos < kv_len
            if causal:
                valid = valid & (kpos <= qpos)
            s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scr[:]                          # [BQ, LANES]
        m_new = jnp.maximum(m_prev, _rows(s.max(axis=1), block_q))
        p = jnp.exp(s - m_new[:, :1])              # [BQ, BK]
        corr = jnp.exp(m_prev - m_new)             # [BQ, LANES]
        l_new = corr * l_scr[:] + _rows(p.sum(axis=1), block_q)
        v = v_ref[0].astype(jnp.float32)           # [BK, D]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [BQ, D]
        acc_scr[:] = corr[:, :1] * acc_scr[:] + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    if causal:
        # skip fully-masked key blocks (they lie strictly above the diagonal)
        @pl.when(kb * block_k <= i * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(kb == nk - 1)
    def _final():
        l = jnp.maximum(l_scr[:], np.float32(1e-30))  # [BQ, LANES]
        o_ref[0] = (acc_scr[:] / l[:, :1]).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:] + jnp.log(l)).astype(jnp.float32)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               kv_len=None):
    bh, sq, d = q.shape
    sk = k.shape[1]
    kv_len = kv_len if kv_len is not None else sk
    nq, nk = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)
    kernel = functools.partial(_fwd_kernel, scale=np.float32(scale), causal=causal,
                               block_q=block_q, block_k=block_k,
                               kv_len=kv_len)
    out_shapes = (jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                  jax.ShapeDtypeStruct((bh, sq, LANES), jnp.float32))
    with _no_x64():
        o, lse = pl.pallas_call(
            kernel,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, kb: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, kb: (b, kb, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, kb: (b, kb, 0)),
            ],
            out_specs=(
                pl.BlockSpec((1, block_q, d), lambda b, i, kb: (b, i, 0)),
                pl.BlockSpec((1, block_q, LANES),
                             lambda b, i, kb: (b, i, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, LANES), jnp.float32),
                pltpu.VMEM((block_q, LANES), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            out_shape=out_shapes,
            interpret=interpret,
        )(q, k, v)
    return o, lse


# ---------------- backward ----------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, causal, block_q, block_k, kv_len):
    i = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                           # [BQ, LANES]
        delta = delta_ref[0]                       # [BQ, LANES]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal or kv_len % block_k:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = kpos < kv_len
            if causal:
                valid = valid & (kpos <= qpos)
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse[:, :1])                # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [BQ, BK]
        ds = p * (dp - delta[:, :1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kb * block_k <= i * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(kb == nk - 1)
    def _final():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                    block_q, block_k, kv_len):
    kb = pl.program_id(1)
    ib = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(ib == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        q = q_ref[0].astype(jnp.float32)           # [BQ, D]
        k = k_ref[0].astype(jnp.float32)           # [BK, D]
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                           # [BQ, LANES]
        delta = delta_ref[0]                       # [BQ, LANES]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal or kv_len % block_k:
            qpos = ib * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            valid = kpos < kv_len
            if causal:
                valid = valid & (kpos <= qpos)
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse[:, :1])                # [BQ, BK]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [BK, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [BQ, BK]
        ds = p * (dp - delta[:, :1]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [BK, D]

    if causal:
        # q blocks strictly above the diagonal contribute nothing
        @pl.when(ib * block_q + block_q - 1 >= kb * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(ib == nq - 1)
    def _final():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, block_q, block_k, interpret, kv_len):
    q, k, v, o, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    do = g
    lse = jnp.broadcast_to(lse, (bh, sq, LANES))  # residual keeps one lane
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [BH, SQ]
    delta = jnp.broadcast_to(delta[..., None], (bh, sq, LANES))
    nq, nk = pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)

    with _no_x64():
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=np.float32(scale),
                              causal=causal, block_q=block_q,
                              block_k=block_k, kv_len=kv_len),
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, kb: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, kb: (b, kb, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, kb: (b, kb, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, kb: (b, i, 0)),
                pl.BlockSpec((1, block_q, LANES),
                             lambda b, i, kb: (b, i, 0)),
                pl.BlockSpec((1, block_q, LANES),
                             lambda b, i, kb: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, kb: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(q, k, v, do, lse, delta)

    with _no_x64():
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=np.float32(scale),
                              causal=causal, block_q=block_q,
                              block_k=block_k, kv_len=kv_len),
            grid=(bh, nk, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, kb, i: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, kb, i: (b, kb, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, kb, i: (b, kb, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, kb, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, LANES),
                             lambda b, kb, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, LANES),
                             lambda b, kb, i: (b, i, 0)),
            ],
            out_specs=(
                pl.BlockSpec((1, block_k, d), lambda b, kb, i: (b, kb, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, kb, i: (b, kb, 0)),
            ),
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)),
            interpret=interpret,
        )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------- public entry ----------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_bhsd(q, k, v, scale, causal, blocks, interpret):
    o, _ = _flash_fwd(q, k, v, scale, causal, blocks[0], blocks[1],
                      interpret, kv_len=blocks[2])
    return o


def _fa_fwd(q, k, v, scale, causal, blocks, interpret):
    o, lse = _flash_fwd(q, k, v, scale, causal, blocks[0], blocks[1],
                        interpret, kv_len=blocks[2])
    # only lane 0 is meaningful — keep one lane in the fwd->bwd residual
    # (128x less HBM held across the backward) and re-broadcast in _flash_bwd
    return o, (q, k, v, o, lse[..., :1])


def _fa_bwd(scale, causal, blocks, interpret, res, g):
    return _flash_bwd(res, g, scale, causal, blocks[0], blocks[1], interpret,
                      kv_len=blocks[2])


_flash_attention_bhsd.defvjp(_fa_fwd, _fa_bwd)


def sharded_flash_attention(mesh, causal=True, scale=None,
                            data_axis="data", model_axis="model",
                            impl=None, block_q=None, block_k=None,
                            interpret=False):
    """Flash attention shard_map'd over the mesh (SNIPPETS [2]
    ``sharded_flash_attention`` shape): q/k/v ``[B, S, H, D]`` partitioned
    ``P(data, None, model, None)`` — batch over the data axis, heads over
    the model axis. Attention is head-local, so every shard runs the full
    kernel on its slice and NO collective appears in the step; the
    out_spec stitches the heads back for GSPMD.

    ``impl(q, k, v)`` defaults to the Pallas kernel; pass the jnp
    reference chain for CPU parity tests (interpret mode measures the
    emulator, not the chip). Degenerate meshes (both axis degrees 1)
    return the plain impl."""
    if impl is None:
        def impl(q, k, v):
            return flash_attention_bshd(q, k, v, causal=causal, scale=scale,
                                        block_q=block_q, block_k=block_k,
                                        interpret=interpret)
    d_deg = int(mesh.shape.get(data_axis, 1))
    m_deg = int(mesh.shape.get(model_axis, 1))
    if d_deg * m_deg <= 1:
        return impl
    from jax.sharding import PartitionSpec as P
    spec = P(data_axis, None, model_axis, None)
    return jax.jit(jax.shard_map(impl, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False))


def flash_attention_bshd(q, k, v, causal=True, scale=None, block_q=None,
                         block_k=None, interpret=False):
    """Flash attention on [B, S, H, D] arrays (paddle layout). Returns the
    same layout. Pads S up to the block size when needed."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = block_q or min(DEFAULT_BLOCK_Q, max(s, 8))
    block_k = block_k or min(DEFAULT_BLOCK_K, max(sk, 8))

    def to_bhsd(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)

    qt, kt, vt = to_bhsd(q), to_bhsd(k), to_bhsd(v)
    pad_q = (-s) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, pad_k), (0, 0)))
    o = _flash_attention_bhsd(qt, kt, vt, scale, causal,
                              (block_q, block_k, sk), interpret)
    if pad_q:
        o = o[:, :s]
    return jnp.swapaxes(o.reshape(b, h, s, d), 1, 2)

