"""Ragged paged attention — mixed prefill+decode rows in ONE launch.

Reference capability: Ragged Paged Attention (arxiv 2604.15464) — the
kernel that lets a serving scheduler stop shaping rounds around the
compile cache. Input is a **flattened token stream**: every row of the
continuous batch (a single-token decode step, a chunked-prefill segment,
a prompt tail behind a prefix-cache hit) contributes its tokens to one
``[total_tokens, H, D]`` query array, described by per-row metadata:

    q            [T, H, D]        flat query tokens, rows back to back
    k/v_cache    [num_pages, page_size, KVH, D]  (GQA pools, KVH <= H)
    row_starts   [R] int32        first flat index of each row's tokens
                                  (nondecreasing; unused rows carry T)
    row_lens     [R] int32        query tokens this launch (0 = unused row)
    kv_lens      [R] int32        TOTAL KV tokens per row AFTER this
                                  launch's writes (prefix + this segment)
    block_tables [R, max_pages]   physical page ids per row

Query token ``i`` of row ``r`` sits at absolute position
``kv_lens[r] - row_lens[r] + i`` and attends causally over its row's
pages: every KV position ``<= `` its own (write-then-attend, the same
order as the decode step — the segment's K/V is already in the pool).
A decode row is simply ``row_lens == 1``; a whole-prompt prefill is
``row_lens == kv_lens``. One launch covers any mix — no (batch, seq)
bucket matrix, no per-shape programs beyond the padded ``T`` itself.

Two backends, same contract as ``paged_attention.py``:

* :func:`ragged_paged_attention_reference` — the jnp gather/segment
  formulation (CPU-parity source of truth): per-token row ids come from
  ``searchsorted`` over ``row_starts`` (the segment decomposition), and
  the causal mask is per-token ``position + 1`` context lengths over the
  row's gathered pages.
* :func:`ragged_paged_attention` — the Pallas kernel: grid
  ``(T, max_pages)``, with the per-token row id and context length in
  **scalar prefetch**, so each grid step's BlockSpec index_map resolves
  ``block_tables[row_ids[t], i]`` and the DMA streams exactly the pages
  the token's row owns. The flat-token grid is what makes the launch
  ragged-native: a token costs its own pages, never a bucket's padding.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .paged_attention import (_grouped, _kernel,
                              paged_attention_reference)

LANES = 128

__all__ = ["ragged_row_index", "ragged_paged_attention_reference",
           "ragged_paged_attention"]


def ragged_row_index(row_starts, row_lens, kv_lens, total_tokens):
    """Per-token segment decomposition of the flat stream — the one copy
    of the ragged index math, shared by the reference, the kernel wrapper
    and the model's pool scatter. For each flat token ``t``:

    * ``row_ids[t]`` — the row owning token ``t`` (``searchsorted`` over
      the nondecreasing ``row_starts``; padding tokens past the last used
      row resolve to it and are masked by ``valid``)
    * ``positions[t]`` — the token's absolute position in its row's KV
      stream (``kv_lens[r] - row_lens[r] + offset``)
    * ``valid[t]`` — False for pad tokens (offset beyond the row's len);
      their writes go to the scrap page and their outputs are garbage the
      caller discards.

    All jnp — safe under jit (``total_tokens`` must be static)."""
    t = jnp.arange(total_tokens, dtype=jnp.int32)
    rs = row_starts.astype(jnp.int32)
    rid = jnp.clip(
        jnp.searchsorted(rs, t, side="right").astype(jnp.int32) - 1,
        0, rs.shape[0] - 1)
    off = t - rs[rid]
    rl = row_lens.astype(jnp.int32)[rid]
    valid = (off >= 0) & (off < rl)
    pos = kv_lens.astype(jnp.int32)[rid] - rl + off
    pos = jnp.where(valid, pos, 0)
    return rid, pos, valid


def ragged_paged_attention_reference(q, k_cache, v_cache, row_starts,
                                     row_lens, kv_lens, block_tables,
                                     scale=None):
    """jnp gather/segment formulation (always-correct path; the serving
    engine's single ragged program compiles this on any device).

    The segment decomposition turns the ragged batch into per-token
    virtual decode rows: token ``t`` attends its row's pages with context
    length ``positions[t] + 1`` — exactly the causal prefix including the
    token's own just-written KV — so the grouped-GQA gather math is ONE
    copy shared with :func:`~.paged_attention.paged_attention_reference`.
    Pad tokens get context 0 (output zeroed). Returns ``[T, H, D]``."""
    T = q.shape[0]
    rid, pos, valid = ragged_row_index(row_starts, row_lens, kv_lens, T)
    vbt = jnp.take(block_tables.astype(jnp.int32), rid, axis=0)  # [T, mp]
    ctx = jnp.where(valid, pos + 1, 0).astype(jnp.int32)
    return paged_attention_reference(q, k_cache, v_cache, vbt, ctx,
                                     scale=scale)


def ragged_paged_attention(q, k_cache, v_cache, row_starts, row_lens,
                           kv_lens, block_tables, scale=None,
                           interpret=False):
    """Pallas kernel: grid ``(T, max_pages)`` over the FLAT token stream.
    Per-token row ids and context lengths ride scalar prefetch next to
    the block tables, so the k/v BlockSpec index_maps resolve
    ``block_tables[row_ids[t], i]`` and the DMA streams each token's own
    row's pages — one launch for any prefill/decode mix, no bucket
    shapes. The online-softmax body is the decode kernel's (a ragged
    token IS a decode row with its own causal context length)."""
    T, H, D = q.shape
    KVH = k_cache.shape[2]
    groups = _grouped(H, KVH)
    num_pages, page_size = k_cache.shape[0], k_cache.shape[1]
    max_pages = block_tables.shape[1]
    scale = np.float32(scale if scale is not None else 1.0 / np.sqrt(D))
    rid, pos, valid = ragged_row_index(row_starts, row_lens, kv_lens, T)
    ctx = jnp.where(valid, pos + 1, 0).astype(jnp.int32)

    def _page(t, i, rid_s, ctx_s, blk):
        # clamp: pad rows carry scrap/garbage table entries; the kernel's
        # in-context mask already zeroes such pages' contribution
        return (jnp.clip(blk[rid_s[t], i], 0, num_pages - 1), 0, 0, 0)

    def _ragged_body(rid_ref, ctx_ref, blk_ref, q_ref, k_ref, v_ref,
                     o_ref, m_scr, l_scr, acc_scr):
        # the decode body verbatim: program_id(0) is the flat token, its
        # context length rides ctx_ref where decode's len_ref sat
        _kernel(blk_ref, ctx_ref, q_ref, k_ref, v_ref, o_ref, m_scr,
                l_scr, acc_scr, scale=scale, page_size=page_size,
                groups=groups)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,   # row_ids, ctx_lens, block_tables
        grid=(T, max_pages),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda t, i, r, c, b: (t, 0, 0)),
            pl.BlockSpec((1, page_size, KVH, D), _page),
            pl.BlockSpec((1, page_size, KVH, D), _page),
        ],
        out_specs=pl.BlockSpec((1, H, D),
                               lambda t, i, r, c, b: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, LANES), jnp.float32),
            pltpu.VMEM((H, LANES), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    with jax.enable_x64(False):
        return pl.pallas_call(
            _ragged_body,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((T, H, D), q.dtype),
            interpret=interpret,
        )(rid, ctx, block_tables.astype(jnp.int32), q, k_cache, v_cache)
