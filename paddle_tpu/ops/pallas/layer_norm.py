"""Fused LayerNorm — Pallas TPU kernel (forward; backward via custom_vjp).

Reference: paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu (the
layer-norm path — mean AND variance, vs the rms path's mean-square only).
One VMEM pass per row-tile computes mean, variance, normalize and affine;
the backward is the (XLA-fused) jnp expression of the analytic gradient,
same split as ops/pallas/rms_norm.py: Pallas where a fused single pass
beats jnp's multiple HBM passes (the fwd), XLA where it already fuses.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

DEFAULT_BLOCK_ROWS = 256

__all__ = ["layer_norm"]


def _kernel(x_ref, w_ref, b_ref, o_ref, *, eps, has_w, has_b):
    x = x_ref[...].astype(jnp.float32)                  # [BR, H]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + np.float32(eps))
    out = xc * inv
    if has_w:
        out = out * w_ref[...].astype(jnp.float32)[None, :]
    if has_b:
        out = out + b_ref[...].astype(jnp.float32)[None, :]
    o_ref[...] = out.astype(o_ref.dtype)


def _fwd_pallas(x2, w, b, eps, block_rows, interpret):
    from ._common import pad_rows_to_grid
    x2, R, br = pad_rows_to_grid(x2, block_rows)
    Rp, H = x2.shape
    grid = (Rp // br,)
    row_spec = pl.BlockSpec((br, H), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((H,), lambda i: (0,))
    has_w, has_b = w is not None, b is not None
    ins = [x2] + ([w] if has_w else []) + ([b] if has_b else [])
    in_specs = [row_spec] + [vec_spec] * (int(has_w) + int(has_b))
    kern = functools.partial(
        _dispatch_kernel, eps=eps, has_w=has_w, has_b=has_b)
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kern, grid=grid, in_specs=in_specs, out_specs=row_spec,
            out_shape=jax.ShapeDtypeStruct((Rp, H), x2.dtype),
            interpret=interpret)(*ins)
    return out[:R] if Rp != R else out


def _dispatch_kernel(x_ref, *refs, eps, has_w, has_b):
    o_ref = refs[-1]
    w_ref = refs[0] if has_w else None
    b_ref = refs[1 if has_w else 0] if has_b else None
    _kernel(x_ref, w_ref, b_ref, o_ref, eps=eps, has_w=has_w, has_b=has_b)


def _bwd_math(x, w, ct, eps, has_b):
    xf = x.astype(jnp.float32)
    ctf = ct.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + np.float32(eps))
    xhat = xc * inv
    ctw = ctf * (w.astype(jnp.float32) if w is not None else 1.0)
    m1 = jnp.mean(ctw, axis=-1, keepdims=True)
    m2 = jnp.mean(ctw * xhat, axis=-1, keepdims=True)
    dx = (inv * (ctw - m1 - xhat * m2)).astype(x.dtype)
    axes = tuple(range(x.ndim - 1))
    dw = jnp.sum(ctf * xhat, axis=axes).astype(
        w.dtype) if w is not None else None
    db = jnp.sum(ctf, axis=axes) if has_b else None  # cast at the caller
    return dx, dw, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ln(x, w, b, eps, block_rows, interpret, has_w, has_b):
    shape = x.shape
    out = _fwd_pallas(x.reshape(-1, shape[-1]),
                      w if has_w else None, b if has_b else None,
                      eps, block_rows, interpret)
    return out.reshape(shape)


def _ln_fwd(x, w, b, eps, block_rows, interpret, has_w, has_b):
    return _ln(x, w, b, eps, block_rows, interpret, has_w, has_b), \
        (x, w, b)


def _ln_bwd(eps, block_rows, interpret, has_w, has_b, res, ct):
    x, w, b = res
    dx, dw, db = _bwd_math(x, w if has_w else None, ct, eps, has_b)
    # cotangent dtypes must match the primals (bf16 x with f32 params is
    # the standard mix — custom_vjp enforces this)
    return (dx,
            dw if dw is not None else jnp.zeros_like(w),
            db.astype(b.dtype) if db is not None else jnp.zeros_like(b))


_ln.defvjp(_ln_fwd, _ln_bwd)


def layer_norm(x, weight=None, bias=None, eps=1e-5,
               block_rows=DEFAULT_BLOCK_ROWS, interpret=False):
    """x: [..., H]; weight/bias: [H] or None. Differentiable."""
    has_w, has_b = weight is not None, bias is not None
    H = x.shape[-1]
    w = weight if has_w else jnp.zeros((H,), x.dtype)  # placeholder
    b = bias if has_b else jnp.zeros((H,), x.dtype)
    return _ln(x, w, b, float(eps), block_rows, interpret, has_w, has_b)
