"""Paged attention (decode) — Pallas TPU kernel + jnp reference path.

Reference capability: paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu (paged KV cache for serving: per-
sequence page tables into a shared block pool, one query token per step).
TPU-native design: the page table rides Pallas scalar prefetch, so each
grid step's BlockSpec index_map looks up the physical page id and the DMA
engine streams exactly the pages a sequence owns — no gather
materialization. Online softmax accumulates across pages (same lane-
replicated stat layout as flash_attention.py).

Layouts:
    q            [B, H, D]          one decode token per sequence
    k/v_cache    [num_pages, page_size, KVH, D]   (KVH <= H: GQA pools —
                 query heads grouped G = H // KVH over shared KV heads)
    block_tables [B, max_pages]     physical page id per logical page
    context_lens [B]                valid KV length per sequence
Returns o [B, H, D].

:func:`paged_prefill_reference` is the chunked-prefill sibling: S query
tokens per row attending the row's pages with a ragged causal mask.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
NEG_INF = np.float32(-1e30)


def _grouped(H, KVH):
    """Query-head group size for GQA pools (KVH kv heads shared across H
    query heads); identity when the pool is classic multi-head."""
    if H % KVH:
        raise ValueError(
            f"{H} query heads not divisible by {KVH} KV heads")
    return H // KVH


def paged_attention_reference(q, k_cache, v_cache, block_tables,
                              context_lens, scale=None):
    """jnp formulation (always-correct path; XLA compiles the page gather).
    Shapes as in the module docstring; GQA-aware — the pools may carry
    ``KVH <= H`` KV heads, query heads grouped ``G = H // KVH``."""
    B, H, D = q.shape
    KVH = k_cache.shape[2]
    G = _grouped(H, KVH)
    page_size = k_cache.shape[1]
    scale = np.float32(scale if scale is not None else 1.0 / np.sqrt(D))
    # clamp sentinel-padded ids: OOB take fills NaN, and 0-weight * NaN
    # would poison the output; clamped pages are masked by context_lens
    block_tables = jnp.clip(block_tables, 0, k_cache.shape[0] - 1)
    # gather each sequence's pages: [B, max_pages, page_size, KVH, D]
    k = jnp.take(k_cache, block_tables, axis=0)
    v = jnp.take(v_cache, block_tables, axis=0)
    S = block_tables.shape[1] * page_size
    k = k.reshape(B, S, KVH, D)
    v = v.reshape(B, S, KVH, D)
    qg = q.reshape(B, KVH, G, D).astype(jnp.float32)
    s = jnp.einsum("bkgd,bskd->bkgs", qg,
                   k.astype(jnp.float32)) * scale
    valid = jnp.arange(S)[None, :] < context_lens[:, None]
    s = jnp.where(valid[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgs,bskd->bkgd", p, v.astype(jnp.float32))
    o = o.reshape(B, H, D)
    # a fully-masked row softmaxes to uniform: zero it (context_len == 0)
    o = jnp.where((context_lens > 0)[:, None, None], o, 0.0)
    return o.astype(q.dtype)


def paged_prefill_reference(q, k_cache, v_cache, block_tables, q_start,
                            q_lens, scale=None):
    """Partial-prefix attention for **chunked prefill** (jnp gather
    formulation; the always-correct path the serving engine's chunk step
    compiles). A chunk of ``S`` query tokens per row starts at absolute
    position ``q_start[b]`` and attends causally over the row's pages —
    which already hold the previously-written prefix PLUS this chunk's own
    K/V (the chunk is scattered into the pool before attending, mirroring
    the decode step's write-then-attend order):

        q            [B, S, H, D]     (rows past ``q_lens[b]`` are padding)
        k/v_cache    [num_pages, page_size, KVH, D]
        block_tables [B, max_pages]
        q_start      [B]   tokens already in the pool before this chunk
        q_lens       [B]   valid query tokens in this chunk

    Query token ``i`` of row ``b`` sees pool positions
    ``<= q_start[b] + i``. Returns ``[B, S, H, D]``; padded query rows
    produce garbage the caller discards (their pool writes were routed to
    the scrap page)."""
    B, S, H, D = q.shape
    KVH = k_cache.shape[2]
    G = _grouped(H, KVH)
    page_size = k_cache.shape[1]
    scale = np.float32(scale if scale is not None else 1.0 / np.sqrt(D))
    block_tables = jnp.clip(block_tables, 0, k_cache.shape[0] - 1)
    k = jnp.take(k_cache, block_tables, axis=0)
    v = jnp.take(v_cache, block_tables, axis=0)
    T = block_tables.shape[1] * page_size
    k = k.reshape(B, T, KVH, D)
    v = v.reshape(B, T, KVH, D)
    qg = q.reshape(B, S, KVH, G, D).astype(jnp.float32)
    s = jnp.einsum("bskgd,btkd->bskgt", qg,
                   k.astype(jnp.float32)) * scale
    key_pos = jnp.arange(T, dtype=jnp.int32)[None, None, :]      # [1,1,T]
    q_pos = (q_start[:, None].astype(jnp.int32)
             + jnp.arange(S, dtype=jnp.int32)[None, :])[:, :, None]
    visible = key_pos <= q_pos                                   # [B,S,T]
    s = jnp.where(visible[:, :, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bskgt,btkd->bskgd", p, v.astype(jnp.float32))
    return o.reshape(B, S, H, D).astype(q.dtype)


def _kernel(blk_ref, len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
            acc_scr, *, scale, page_size, groups):
    b = pl.program_id(0)
    i = pl.program_id(1)
    n = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)               # [H, D]
    k = k_ref[0].astype(jnp.float32)               # [page, KVH, D]
    v = v_ref[0].astype(jnp.float32)
    H, D = q.shape
    kvh = H // groups
    kt = jnp.swapaxes(k, 0, 1)                     # [KVH, page, D]
    vt = jnp.swapaxes(v, 0, 1)
    # grouped-query scores: query heads [KVH, G] batch over their shared
    # KV head, then flatten back to the [H, page] stat layout
    qg = q.reshape(kvh, groups, D)
    s = jax.lax.dot_general(
        qg, kt, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).reshape(H, -1) * scale
    pos = i * page_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    in_ctx = pos < len_ref[b]
    s = jnp.where(in_ctx, s, NEG_INF)
    m_prev = m_scr[:]                              # [H, LANES]
    m_new = jnp.maximum(m_prev, jax.lax.broadcast_in_dim(
        s.max(axis=1), m_prev.shape, (0,)))
    # mask explicitly: when every position is masked m_new == NEG_INF and
    # exp(s - m_new) == 1, which would average garbage V pages (a padded
    # block table points anywhere) instead of contributing nothing
    p = jnp.where(in_ctx, jnp.exp(s - m_new[:, :1]),
                  np.float32(0.0))                 # [H, page]
    corr = jnp.exp(m_prev - m_new)
    l_scr[:] = corr * l_scr[:] + jax.lax.broadcast_in_dim(
        p.sum(axis=1), m_prev.shape, (0,))
    pv = jax.lax.dot_general(
        p.reshape(kvh, groups, -1), vt, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).reshape(H, D)
    acc_scr[:] = corr[:, :1] * acc_scr[:] + pv
    m_scr[:] = m_new

    @pl.when(i == n - 1)
    def _final():
        o_ref[0] = (acc_scr[:]
                    / jnp.maximum(l_scr[:, :1], np.float32(1e-30))) \
            .astype(o_ref.dtype)


def paged_attention(q, k_cache, v_cache, block_tables, context_lens,
                    scale=None, interpret=False):
    """Pallas kernel: grid (B, max_pages); the k/v BlockSpec index_maps read
    the scalar-prefetched page table, so the DMA streams each sequence's
    physical pages directly."""
    B, H, D = q.shape
    KVH = k_cache.shape[2]
    groups = _grouped(H, KVH)
    num_pages, page_size = k_cache.shape[0], k_cache.shape[1]
    max_pages = block_tables.shape[1]
    scale = np.float32(scale if scale is not None else 1.0 / np.sqrt(D))

    def _page(b, i, blk, ln):
        # clamp: tables padded past context_lens (sentinel -1 or any id)
        # must not drive an out-of-bounds block DMA; the kernel's in_ctx
        # mask already zeroes such pages' contribution
        return (jnp.clip(blk[b, i], 0, num_pages - 1), 0, 0, 0)

    kernel = functools.partial(_kernel, scale=scale, page_size=page_size,
                               groups=groups)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,   # block_tables, context_lens
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, i, blk, ln: (b, 0, 0)),
            pl.BlockSpec((1, page_size, KVH, D), _page),
            pl.BlockSpec((1, page_size, KVH, D), _page),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, i, blk, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, LANES), jnp.float32),
            pltpu.VMEM((H, LANES), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    with jax.enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
            interpret=interpret,
        )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
          q, k_cache, v_cache)


def paged_attention_trainable(q, k_cache, v_cache, block_tables,
                              context_lens, scale=None, interpret=False):
    """Pallas forward + reference-path backward: the scalar-prefetch grid
    spec has no JVP rule, so jax.vjp through the raw kernel raises — this
    custom_vjp keeps the fast forward and differentiates through the
    mathematically-identical gather formulation."""

    @jax.custom_vjp
    def run(q, kc, vc):
        return paged_attention(q, kc, vc, block_tables, context_lens,
                               scale=scale, interpret=interpret)

    def fwd(q, kc, vc):
        return run(q, kc, vc), (q, kc, vc)

    def bwd(res, ct):
        q, kc, vc = res
        _, vjp = jax.vjp(
            lambda a, b, c: paged_attention_reference(
                a, b, c, block_tables, context_lens, scale=scale),
            q, kc, vc)
        return vjp(ct)

    run.defvjp(fwd, bwd)
    return run(q, k_cache, v_cache)
