"""Shared Pallas kernel scaffolding + the kernel demotion gate.

**The standing kernel rule (ROADMAP item 1):** a Pallas kernel serves on
the default path only where it MEASURABLY beats its XLA counterpart at the
exact shape, on the real chip. BENCH_r05 showed all three fused kernels
(AdamW, rms_norm, layer_norm) losing to plain jnp/XLA on the v5e — the
gate generalizes ``serving/decode.py``'s A/B mechanism to every kernel:

* ``PADDLE_TPU_KERNELS=xla|pallas|auto`` (default ``auto``) — ``xla``
  demotes every kernel, ``pallas`` forces every eligible kernel (still
  TPU-only; interpret mode is an emulator, not a measurement), ``auto``
  consults the verdict cache.
* :func:`ab_gate` times the jitted XLA reference against the Pallas kernel
  at one exact shape and caches the verdict per ``(kernel, shape sig)``.
  ``bench.py``'s kernels leg (and the serving engine at startup) run it
  eagerly and record one A/B row per kernel in the snapshot JSON.
* :func:`pallas_default` is the cheap per-call-site query: under ``auto``
  with no measured verdict it answers **False** — unmeasured kernels are
  demoted, never promoted on faith. Measurement never happens implicitly
  inside user code or under tracing (you cannot time a tracer).

Verdicts are process-local by default; :func:`nearest_verdict` lets size-
polymorphic callers (the fused optimizer sweeping many param shapes) reuse
a same-dtype/same-rank verdict within a 4x size band. Set
``PADDLE_TPU_KERNELS_CACHE=<path>`` to persist verdicts ACROSS processes
as JSON (PR-7 follow-up c): the file is loaded lazily on the first verdict
query (in-memory measurements win over file rows), merged and atomically
re-saved on every :func:`record_verdict` — so a bench run warms the cache
and later user jobs start with measured verdicts instead of the
demote-unproven default.
"""
from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

__all__ = ["pad_rows_to_grid", "kernels_mode", "on_tpu", "shape_sig",
           "pallas_default", "ab_gate", "record_verdict", "get_verdict",
           "nearest_verdict", "gate_report", "save_verdicts",
           "KERNELS_ENV", "KERNELS_CACHE_ENV"]

KERNELS_ENV = "PADDLE_TPU_KERNELS"
KERNELS_CACHE_ENV = "PADDLE_TPU_KERNELS_CACHE"
_MODES = ("xla", "pallas", "auto")

# (kernel name, shape sig) -> {"backend", "xla_ms", "pallas_ms", "reason"}
_verdicts: dict = {}
_cache_loaded = False

# auto-mode behavior when NO verdict (exact or nearest) exists for a shape.
# flash_attention is the incumbent winner (it carried the MFU headline
# before the gate existed and was never among BENCH_r05's losers), so an
# unmeasured process keeps serving it — demotion needs a measured LOSS.
# The kernels BENCH_r05 caught losing on-chip (fused AdamW, rms_norm,
# layer_norm) plus paged_attention (the serving engine measures at
# startup anyway) stay demoted until a measured win promotes them.
_UNMEASURED_DEFAULT = {"flash_attention": True}


def _reset_state():
    """Drop every cached A/B verdict (tests) and forget whether the
    persistent cache file was loaded."""
    global _cache_loaded
    _verdicts.clear()
    _cache_loaded = False


# ----------------------------------------------- cross-process persistence

def _sig_to_json(sig):
    return [[list(s), d] for s, d in sig]


def _sig_from_json(j):
    return tuple((tuple(int(x) for x in s), str(d)) for s, d in j)


def _load_cache():
    """Lazy one-shot load of ``PADDLE_TPU_KERNELS_CACHE``. File rows never
    override verdicts measured in THIS process (fresher hardware truth)."""
    global _cache_loaded
    if _cache_loaded:
        return
    _cache_loaded = True
    path = os.environ.get(KERNELS_CACHE_ENV)
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            rows = json.load(f)
        for r in rows:
            _verdicts.setdefault((r["kernel"], _sig_from_json(r["sig"])),
                                 r["row"])
    except Exception as e:
        import sys
        print(f"[kernels] {KERNELS_CACHE_ENV}={path}: load failed "
              f"({type(e).__name__}: {e}); starting from empty verdicts",
              file=sys.stderr, flush=True)


def save_verdicts(path=None):
    """Merge the in-memory verdicts into the cache file and atomically
    replace it (tmp + ``os.replace`` — a concurrent reader never sees a
    torn file). Rows already on disk for other shapes survive. Returns
    the path, or None when no cache is configured.

    Called on every :func:`record_verdict` by design: verdicts arrive
    only from explicit measurement (a bench leg, serving startup —
    dozens per process at most, never a hot loop), the file is KB-scale,
    and saving immediately means a crash mid-sweep keeps everything
    measured so far."""
    path = path or os.environ.get(KERNELS_CACHE_ENV)
    if not path:
        return None
    merged: dict = {}
    try:
        if os.path.exists(path):
            with open(path) as f:
                for r in json.load(f):
                    merged[(r["kernel"], _sig_from_json(r["sig"]))] = \
                        r["row"]
    except Exception:
        pass  # a corrupt file is replaced wholesale
    merged.update(_verdicts)
    rows = [{"kernel": k, "sig": _sig_to_json(s), "row": row}
            for (k, s), row in sorted(merged.items(), key=str)]
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rows, f, indent=1)
        os.replace(tmp, path)
    except Exception as e:
        import sys
        print(f"[kernels] {KERNELS_CACHE_ENV}={path}: save failed "
              f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)
        return None
    return path


def kernels_mode() -> str:
    """Resolve the global kernel-selection knob."""
    mode = (os.environ.get(KERNELS_ENV) or "auto").lower()
    if mode not in _MODES:
        raise ValueError(
            f"{KERNELS_ENV}={mode!r}: pick from {_MODES}")
    return mode


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def shape_sig(*arrays):
    """Exact-shape signature: ((shape, dtype), ...) over the operands that
    determine the kernel's grid. Works on tracers (shape/dtype are
    static)."""
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)


def get_verdict(kernel, sig):
    _load_cache()
    return _verdicts.get((kernel, sig))


def record_verdict(kernel, sig, row):
    _load_cache()
    _verdicts[(kernel, sig)] = row
    if os.environ.get(KERNELS_CACHE_ENV):
        save_verdicts()


def nearest_verdict(kernel, sig, size_band=4.0):
    """A measured verdict for the same kernel whose leading operand has the
    same dtype and a total size within ``size_band``x — the fused optimizer
    sweeps param shapes and re-timing every one would cost more than it
    saves. Rank is deliberately NOT matched: the elementwise/row-tiled
    kernels care about total element count (bench measures fused AdamW on
    a flat 8M vector, real params are 2-D; norm call sites see [B, S, H]
    activations against a 2-D bench verdict)."""
    _load_cache()
    if not sig:
        return None
    want_shape, want_dtype = sig[0]
    want_size = 1
    for d in want_shape:
        want_size *= max(int(d), 1)
    best = None
    for (k, s), row in _verdicts.items():
        if k != kernel or not s:
            continue
        shape, dtype = s[0]
        if dtype != want_dtype:
            continue
        size = 1
        for d in shape:
            size *= max(int(d), 1)
        ratio = size / want_size if want_size else float("inf")
        if 1.0 / size_band <= ratio <= size_band:
            if best is None or abs(ratio - 1.0) < best[0]:
                best = (abs(ratio - 1.0), row)
    return best[1] if best else None


def pallas_default(kernel, sig, allow_nearest=False):
    """Should this call site take the Pallas path? ``xla`` → never;
    ``pallas`` → always (the caller still owns TPU-eligibility);
    ``auto`` → a measured win at this (or, optionally, a nearby) shape,
    falling back to the kernel's unmeasured default (incumbent winners
    keep serving; measured losers and unproven kernels demote). One env
    read + one dict lookup on the no-verdict path."""
    mode = kernels_mode()
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    _load_cache()
    row = _verdicts.get((kernel, sig))
    if row is None and allow_nearest:
        row = nearest_verdict(kernel, sig)
    if row is None:
        return _UNMEASURED_DEFAULT.get(kernel, False)
    return row.get("backend") == "pallas"


def _time_jitted(fn, args, repeats):
    out = fn(*args)           # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / repeats * 1e3


def ab_gate(kernel, xla_fn, pallas_fn, args, repeats=10, record=True,
            sig=None):
    """Time the jitted XLA reference vs the Pallas kernel at this exact
    shape and cache the verdict. Off-TPU the Pallas leg is skipped
    (interpret mode measures the emulator, not the chip) and XLA wins by
    default; a Pallas failure (unsupported shape/dtype) also demotes.
    ``sig`` overrides the recorded signature — it must match what the
    kernel's call site queries (e.g. flash attention gates on (q, k)
    while the timing needs (q, k, v)).
    -> ``{"backend", "xla_ms", "pallas_ms", "reason"}``."""
    for a in args:
        if isinstance(a, jax.core.Tracer):
            raise RuntimeError(
                f"ab_gate({kernel!r}) needs concrete operands — it cannot "
                "time a tracer; run it eagerly (bench kernels leg, serving "
                "warmup) before compiling the consumer")
    if sig is None:
        sig = shape_sig(*args)
    mode = kernels_mode()
    row = {"backend": "xla", "xla_ms": None, "pallas_ms": None,
           "reason": "xla reference"}
    if mode in ("xla", "pallas"):
        row["backend"] = mode
        row["reason"] = f"forced by {KERNELS_ENV}={mode}"
        # NOT recorded: a forced row is policy, not a measurement — if it
        # entered the verdict cache, flipping the env back to auto in the
        # same process would serve an untimed kernel as if it had won
        return row
    xla_ms = _time_jitted(jax.jit(xla_fn), args, repeats)
    row["xla_ms"] = round(xla_ms, 4)
    if not on_tpu():
        row["reason"] = "pallas requires TPU (interpret-only here)"
        if record:
            record_verdict(kernel, sig, row)
        return row
    try:
        pallas_ms = _time_jitted(jax.jit(pallas_fn), args, repeats)
    except Exception as e:  # unsupported shape/dtype: gate stays on XLA
        row["reason"] = f"pallas failed: {type(e).__name__}: {e}"[:160]
        if record:
            record_verdict(kernel, sig, row)
        return row
    row["pallas_ms"] = round(pallas_ms, 4)
    if pallas_ms < xla_ms:
        row["backend"] = "pallas"
        row["reason"] = "pallas beat xla at this shape"
    else:
        row["reason"] = "xla beat pallas at this shape"
    if record:
        record_verdict(kernel, sig, row)
    return row


def gate_report():
    """Every cached verdict, keyed ``kernel[shapes]`` — the bench snapshot
    embeds this so each round records which kernels were demoted where."""
    _load_cache()
    out = {}
    for (kernel, sig), row in sorted(_verdicts.items(), key=str):
        label = ",".join("x".join(map(str, s)) + f":{d}" for s, d in sig)
        out[f"{kernel}[{label}]"] = row
    return out


def pad_rows_to_grid(x2, block_rows):
    """Pad a [R, H] operand so R divides the row-block size.

    Row-tiled kernels must not fall back to one giant [R, H] block when R
    is not divisible (a single block must fit VMEM, ~16 MB); padding the
    grid and slicing the output back is the safe general form. Returns
    (padded, R, br): the original row count and the block size to use.
    """
    R, H = x2.shape
    br = min(block_rows, R)
    pad = (-R) % br
    if pad:
        x2 = jnp.concatenate([x2, jnp.zeros((pad, H), x2.dtype)], axis=0)
    return x2, R, br
