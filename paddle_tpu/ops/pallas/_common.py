"""Shared Pallas kernel scaffolding."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["pad_rows_to_grid"]


def pad_rows_to_grid(x2, block_rows):
    """Pad a [R, H] operand so R divides the row-block size.

    Row-tiled kernels must not fall back to one giant [R, H] block when R
    is not divisible (a single block must fit VMEM, ~16 MB); padding the
    grid and slicing the output back is the safe general form. Returns
    (padded, R, br): the original row count and the block size to use.
    """
    R, H = x2.shape
    br = min(block_rows, R)
    pad = (-R) % br
    if pad:
        x2 = jnp.concatenate([x2, jnp.zeros((pad, H), x2.dtype)], axis=0)
    return x2, R, br
