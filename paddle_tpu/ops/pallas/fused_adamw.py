"""Fused AdamW update — Pallas TPU kernel.

Reference: paddle/phi/kernels/gpu/fused_adam_kernel.cu (multi-tensor Adam:
one launch updates param+moments together instead of a kernel per
elementwise op). On TPU, XLA already fuses the per-parameter update chain;
the Pallas kernel removes the remaining multi-pass HBM traffic by reading
each (param, grad, m, v) tile into VMEM once and writing all three results
from the same pass — the fused_adam capability, Mosaic-style.

Semantics match optimizer/optimizers.py Adam/AdamW exactly:
    w   <- w * (1 - lr*wd)                      (decoupled decay)
    m   <- b1*m + (1-b1)*g
    v   <- b2*v + (1-b2)*g^2
    w   <- w - lr * (m*bc1) / (sqrt(v*bc2) + eps)
with bc1 = 1/(1-b1^t), bc2 = 1/(1-b2^t) computed by the caller (t may be a
traced step counter).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
DEFAULT_ROWS = 512  # 512x128 f32 tiles: 256KB per operand in VMEM


def _kernel(sc_ref, w_ref, g_ref, m_ref, v_ref, wo_ref, mo_ref, vo_ref):
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    bc1 = sc_ref[5]
    bc2 = sc_ref[6]
    g = g_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32) * (np.float32(1.0) - lr * wd)
    m = b1 * m_ref[...] + (np.float32(1.0) - b1) * g
    v = b2 * v_ref[...] + (np.float32(1.0) - b2) * g * g
    w = w - lr * (m * bc1) / (jnp.sqrt(v * bc2) + eps)
    wo_ref[...] = w.astype(wo_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def _pad_rows(a, rows, dtype=None):
    flat = a.reshape(-1)
    n = flat.shape[0]
    pad = rows * LANES - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    out = flat.reshape(rows, LANES)
    return out.astype(dtype) if dtype is not None else out


def fused_adamw(w, g, m, v, lr, beta1, beta2, eps, wd, bc1, bc2,
                block_rows=DEFAULT_ROWS, interpret=False):
    """One-pass AdamW update. Returns (w', m', v') with w's dtype/shape."""
    shape, n = w.shape, w.size
    rows = -(-n // LANES)
    rows = -(-rows // 8) * 8  # sublane alignment
    block_rows = min(block_rows, rows)
    if rows % block_rows:
        block_rows = rows  # small param: single block
    scalars = jnp.stack([jnp.asarray(s, jnp.float32)
                         for s in (lr, beta1, beta2, eps, wd, bc1, bc2,
                                   0.0)])
    w2 = _pad_rows(w, rows)
    g2 = _pad_rows(g, rows, jnp.float32)
    m2 = _pad_rows(m, rows, jnp.float32)
    v2 = _pad_rows(v, rows, jnp.float32)
    grid = (rows // block_rows,)
    spec = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))
    with jax.enable_x64(False):
        wo, mo, vo = pl.pallas_call(
            _kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                spec, spec, spec, spec,
            ],
            out_specs=(spec, spec, spec),
            out_shape=(
                jax.ShapeDtypeStruct((rows, LANES), w.dtype),
                jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
                jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            ),
            interpret=interpret,
        )(scalars, w2, g2, m2, v2)
    unpad = lambda a, dt: a.reshape(-1)[:n].reshape(shape).astype(dt)
    return (unpad(wo, w.dtype), unpad(mo, jnp.float32),
            unpad(vo, jnp.float32))
