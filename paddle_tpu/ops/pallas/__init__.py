"""Pallas TPU kernels (SURVEY §7 step 9: the hot fused set).

These back the functional layer transparently; each has an XLA fallback.
"""
from .flash_attention import flash_attention_bshd  # noqa: F401
from .paged_attention import (  # noqa: F401
    paged_attention, paged_attention_reference,
)
