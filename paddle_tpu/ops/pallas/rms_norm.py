"""Fused RMSNorm — Pallas TPU kernel (forward; backward via custom_vjp).

Reference: paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu (the
rms-norm path). One VMEM pass per row-tile computes the mean-square,
rsqrt, and scale in place of the jnp composition's multiple HBM passes.
The backward is the (XLA-fused) jnp expression of the analytic gradient —
one fused kernel either way, so Pallas is spent where it pays (the fwd).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

DEFAULT_BLOCK_ROWS = 256


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)            # [BR, H]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)  # [BR, 1]
    inv = jax.lax.rsqrt(ms + np.float32(eps))
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)[None, :]) \
        .astype(o_ref.dtype)


def _kernel_bias(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + np.float32(eps))
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)[None, :]
                  + b_ref[...].astype(jnp.float32)[None, :]) \
        .astype(o_ref.dtype)


def _fwd_pallas(x2, w, b, eps, block_rows, interpret):
    from ._common import pad_rows_to_grid
    x2, R, br = pad_rows_to_grid(x2, block_rows)
    Rp, H = x2.shape
    grid = (Rp // br,)
    row_spec = pl.BlockSpec((br, H), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((H,), lambda i: (0,))
    with jax.enable_x64(False):
        if b is None:
            out = pl.pallas_call(
                functools.partial(_kernel, eps=eps),
                grid=grid,
                in_specs=[row_spec, vec_spec],
                out_specs=row_spec,
                out_shape=jax.ShapeDtypeStruct((Rp, H), x2.dtype),
                interpret=interpret,
            )(x2, w)
        else:
            out = pl.pallas_call(
                functools.partial(_kernel_bias, eps=eps),
                grid=grid,
                in_specs=[row_spec, vec_spec, vec_spec],
                out_specs=row_spec,
                out_shape=jax.ShapeDtypeStruct((Rp, H), x2.dtype),
                interpret=interpret,
            )(x2, w, b)
    return out[:R] if Rp != R else out


def _bwd_math(x, w, ct, eps):
    xf = x.astype(jnp.float32)
    ctf = ct.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    xhat = xf * inv
    ctw = ctf * wf
    dx = inv * (ctw - xhat * jnp.mean(ctw * xhat, axis=-1, keepdims=True))
    axes = tuple(range(x.ndim - 1))
    dw = jnp.sum(ctf * xhat, axis=axes).astype(w.dtype)
    return dx.astype(x.dtype), dw, ctf, axes


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rms_nb(x, w, eps, block_rows, interpret):
    shape = x.shape
    out = _fwd_pallas(x.reshape(-1, shape[-1]), w, None, eps, block_rows,
                      interpret)
    return out.reshape(shape)


def _rms_nb_fwd(x, w, eps, block_rows, interpret):
    return _rms_nb(x, w, eps, block_rows, interpret), (x, w)


def _rms_nb_bwd(eps, block_rows, interpret, res, ct):
    x, w = res
    dx, dw, _, _ = _bwd_math(x, w, ct, eps)
    return dx, dw


_rms_nb.defvjp(_rms_nb_fwd, _rms_nb_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _rms_b(x, w, b, eps, block_rows, interpret):
    shape = x.shape
    out = _fwd_pallas(x.reshape(-1, shape[-1]), w, b, eps, block_rows,
                      interpret)
    return out.reshape(shape)


def _rms_b_fwd(x, w, b, eps, block_rows, interpret):
    return _rms_b(x, w, b, eps, block_rows, interpret), (x, w, b)


def _rms_b_bwd(eps, block_rows, interpret, res, ct):
    x, w, b = res
    dx, dw, ctf, axes = _bwd_math(x, w, ct, eps)
    db = jnp.sum(ctf, axis=axes).astype(b.dtype)
    return dx, dw, db


_rms_b.defvjp(_rms_b_fwd, _rms_b_bwd)


def rms_norm(x, w, b=None, eps=1e-6, block_rows=DEFAULT_BLOCK_ROWS,
             interpret=False):
    """x: [..., H]; w/b: [H]. Returns x's shape/dtype. Differentiable."""
    if b is None:
        return _rms_nb(x, w, float(eps), block_rows, interpret)
    return _rms_b(x, w, b, float(eps), block_rows, interpret)
