"""paddle.incubate.optimizer — LookAhead, ModelAverage.

Reference: python/paddle/incubate/optimizer/{lookahead,modelaverage}.py.
Pure optimizer-state logic over the framework arrays; each update is one
fused XLA program per parameter.
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np

__all__ = ["DistributedFusedLamb", "LookAhead", "ModelAverage"]


class LookAhead:
    """k steps forward, 1 step back (reference: lookahead.py LookAhead).

    Wraps an inner optimizer; every k inner steps the slow weights move
    alpha of the way toward the fast weights and the fast weights reset to
    the slow ones. Slow weights are captured at construction (reference
    behavior); call capture_slow() to re-capture later."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        assert 0.0 <= alpha <= 1.0 and k >= 1
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {}
        self.capture_slow()

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def capture_slow(self):
        """Record current params as the slow weights."""
        for p in self.inner_optimizer._parameter_list:
            self._slow[id(p)] = p._data

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        a = np.float32(self.alpha)
        for p in self.inner_optimizer._parameter_list:
            slow = self._slow.get(id(p), p._data)
            new_slow = slow + a * (p._data - slow)
            self._slow[id(p)] = new_slow
            p._data = new_slow.astype(p._data.dtype)

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        # slow weights keyed by parameter position (ids don't survive a
        # process restart)
        sd["lookahead_slow"] = [
            np.asarray(self._slow[id(p)])
            if id(p) in self._slow else None
            for p in self.inner_optimizer._parameter_list]
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)  # don't mutate the caller's dict
        self._step_num = sd.pop("lookahead_step", 0)
        slows = sd.pop("lookahead_slow", None)
        if slows is not None:
            for p, s in zip(self.inner_optimizer._parameter_list, slows):
                if s is not None:
                    self._slow[id(p)] = jnp.asarray(s)
        self.inner_optimizer.set_state_dict(sd)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None

    def __getattr__(self, name):
        if name == "inner_optimizer":  # guard: deepcopy/pickle probe attrs
            raise AttributeError(name)  # before __init__ has run
        return getattr(self.inner_optimizer, name)


class ModelAverage:
    """Running average of parameters applied at eval time (reference:
    modelaverage.py). The averaging window grows with training:
    window = clip(average_window_rate * num_updates,
                  min_average_window, max_average_window); accumulation
    restarts when the window is exceeded so early weights age out."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._params = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._params}
        self._count = 0
        self._num_updates = 0
        self._backup = None

    def _window(self):
        return int(min(max(self.rate * max(self._num_updates, 1),
                           self.min_window), self.max_window))

    def step(self):
        """Accumulate after each optimizer step."""
        self._num_updates += 1
        self._count += 1
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._data
        if self._count > self._window():
            # restart the window: recent weights only (reference restart)
            for p in self._params:
                self._sum[id(p)] = p._data
            self._count = 1

    def apply(self, executor=None, need_restore=True):
        """Swap in averaged params (reference apply()); with
        need_restore=False the averaged weights become permanent."""
        if self._count == 0:
            warnings.warn("ModelAverage.apply() before any step(): "
                          "parameters left unchanged")
            return
        self._backup = {id(p): p._data for p in self._params} \
            if need_restore else None
        c = np.float32(self._count)
        for p in self._params:
            p._data = (self._sum[id(p)] / c).astype(p._data.dtype)

    def restore(self, executor=None):
        if self._backup:
            for p in self._params:
                p._data = self._backup[id(p)]
            self._backup = None


class DistributedFusedLamb:
    """Reference: incubate/optimizer/distributed_fused_lamb.py — the
    multi-tensor fused LAMB. TPU-native: paddle.optimizer.Lamb's update is
    already a single fused XLA kernel per parameter and composes with
    ZeRO sharding, so this class delegates (the CUDA multi-tensor fusion
    is the mechanism, not the capability)."""

    def __new__(cls, learning_rate=0.001, lamb_weight_decay=0.01,
                beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                grad_clip=None, exclude_from_weight_decay_fn=None,
                clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                use_master_param_norm=True, gradient_accumulation_steps=1,
                use_master_acc_grad=True, nproc_per_node=None, name=None):
        from ...optimizer import Lamb
        return Lamb(learning_rate=learning_rate,
                    lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                    beta2=beta2, epsilon=epsilon, parameters=parameters,
                    grad_clip=grad_clip,
                    exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)
