"""paddle.incubate.autograd (reference: python/paddle/incubate/autograd —
primitive-based functional autodiff: jvp/vjp/Jacobian/Hessian, prim2orig
switches). TPU-native: jax IS the primitive autodiff system, so the
functional surface re-exports the tape-level implementations and the
prim switches are honest no-ops (always-on)."""
from ...autograd.functional import hessian, jacobian, jvp, vjp  # noqa: F401

Jacobian = jacobian
Hessian = hessian


def forward_grad(outputs, inputs, grad_inputs=None):
    """Reference: incubate/autograd/primapi.py forward_grad — jvp with
    default tangents of ones."""
    out, tangents = jvp(lambda *xs: outputs(*xs) if callable(outputs)
                        else outputs, inputs, grad_inputs)
    return tangents


def grad(outputs, inputs, grad_outputs=None):
    from ...core.autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs)


def enable_prim():
    return None  # jax primitives are always on


def disable_prim():
    return None


def prim_enabled():
    return True
