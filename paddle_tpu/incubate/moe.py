"""Mixture-of-Experts with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py
(GShard-style all-to-all MoE) + gates (gate/{naive,gshard,switch}_gate.py)
and the dispatch kernels (assign_pos/limit_by_capacity).

TPU-native: the classic scatter/gather dispatch becomes the GShard einsum
formulation — dispatch/combine are one-hot matmuls over a capacity-limited
[tokens, experts, capacity] mask, and the expert FFNs are ONE batched matmul
over stacked weights [E, d, h] sharded on the expert axis. When the
dispatched tensor's expert dim is sharded, GSPMD emits exactly the all-to-all
the reference issues by hand — and it rides ICI.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import nn

__all__ = ["MoELayer", "TopKGate"]


class TopKGate(nn.Layer):
    """top-1 (switch) / top-2 (gshard) softmax gate with load-balance loss.

    Reference: moe/gate/gshard_gate.py, switch_gate.py.
    """

    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=1.25):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter([d_model, num_experts])

    def capacity(self, num_tokens):
        return int(math.ceil(num_tokens / self.num_experts
                             * self.capacity_factor * self.top_k))


class MoELayer(nn.Layer):
    """Reference: incubate/distributed/models/moe/moe_layer.py MoELayer.

    Expert FFN: x → gelu(x @ wi[e]) @ wo[e]. Experts stacked on dim 0 and
    sharded over the expert-parallel mesh axis (``moe_group``).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, moe_group=None, gate=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.gate = gate or TopKGate(d_model, num_experts, top_k,
                                     capacity_factor)
        self.wi = self.create_parameter([num_experts, d_model, d_hidden])
        self.wo = self.create_parameter([num_experts, d_hidden, d_model])
        self._group = moe_group
        if moe_group is not None:
            sharding = NamedSharding(moe_group.mesh,
                                     P(moe_group.axis, None, None))
            self.wi._data = jax.device_put(self.wi._data, sharding)
            self.wo._data = jax.device_put(self.wo._data, sharding)
        self.aux_loss = None

    def forward(self, x):
        """x: [..., d_model] → same shape. Sets self.aux_loss (load-balance,
        GShard eq.4) as a taped scalar for the training loss."""
        E = self.num_experts
        lead_shape = x.shape[:-1]
        n_tokens = int(np.prod(lead_shape))
        C = self.gate.capacity(n_tokens)
        top_k = self.top_k

        def fwd(xa, wg, wi, wo):
            xt = xa.reshape(n_tokens, self.d_model)
            logits = jnp.matmul(xt.astype(jnp.float32),
                                wg.astype(jnp.float32))      # [T, E]
            probs = jax.nn.softmax(logits, axis=-1)

            # top-k routing with capacity limiting (GShard)
            combine = jnp.zeros((n_tokens, E, C), jnp.float32)
            dispatch = jnp.zeros((n_tokens, E, C), bool)
            remaining = probs
            # position counters are built with cumsum per expert
            used = jnp.zeros((E,), jnp.int32)
            masks = []
            gates_k = []
            for _ in range(top_k):
                idx = jnp.argmax(remaining, axis=-1)          # [T]
                onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
                gates_k.append((remaining * onehot).sum(-1))  # [T]
                masks.append(onehot)
                remaining = remaining * (1 - onehot)
            if top_k > 1:
                # renormalize the k gate values (GShard)
                denom = sum(gates_k) + 1e-9
                gates_k = [g / denom for g in gates_k]
            # top_k == 1 keeps the raw top-1 probability as the combine
            # weight (reference switch_gate.py) so the gate gets gradient
            # through the expert output, not only the aux loss.

            pos_base = jnp.zeros((E,), jnp.float32)
            for onehot, gval in zip(masks, gates_k):
                # position of each token within its expert's capacity
                pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) \
                    + pos_base[None, :]                        # [T, E]
                keep = (pos_in_expert < C) & (onehot > 0)
                pos = jnp.clip(pos_in_expert.astype(jnp.int32), 0, C - 1)
                cap_onehot = jax.nn.one_hot(pos, C,
                                            dtype=jnp.float32) * \
                    keep[..., None]                            # [T, E, C]
                combine = combine + cap_onehot * gval[:, None, None]
                dispatch = dispatch | (cap_onehot > 0)
                pos_base = pos_base + onehot.sum(axis=0)

            # dispatch tokens: [E, C, M]
            dispatched = jnp.einsum("tec,tm->ecm",
                                    dispatch.astype(xt.dtype), xt)
            h = jnp.einsum("ecm,emh->ech", dispatched, wi.astype(xt.dtype))
            h = jax.nn.gelu(h)
            eo = jnp.einsum("ech,ehm->ecm", h, wo.astype(xt.dtype))
            out = jnp.einsum("tec,ecm->tm", combine.astype(xt.dtype), eo)

            # load-balance aux loss (GShard): E * sum_e f_e * p_e
            me = probs.mean(axis=0)                           # [E]
            ce = masks[0].mean(axis=0)                        # top-1 fraction
            aux = (me * ce).sum() * E
            return out.reshape(xa.shape), aux

        out, aux = apply("moe", fwd, [x, self.gate.weight, self.wi, self.wo],
                         nout=2)
        self.aux_loss = aux
        return out
