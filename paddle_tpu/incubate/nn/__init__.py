"""paddle.incubate.nn — fused Layer classes over the functional fused ops.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py (the
Layer-with-parameters wrappers around the fused CUDA entry points). Each
class here owns the parameters and defers the math to
incubate.nn.functional — which is one taped op (XLA fuses it), riding the
Pallas kernels where eligible.
"""
from __future__ import annotations

from ...nn import Layer
from ...nn import initializer as I
from . import functional as F_inc

functional = F_inc  # the public submodule name

__all__ = ["FusedLinear", "FusedDropoutAdd", "FusedEcMoe",
           "FusedBiasDropoutResidualLayerNorm", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer",
           "FusedMultiTransformer"]


class FusedLinear(Layer):
    """Reference: incubate/nn/layer/fused_linear.py."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)
        self._transpose_weight = transpose_weight

    def forward(self, x):
        return F_inc.fused_linear(x, self.weight, self.bias,
                                  transpose_weight=self._transpose_weight)


class FusedDropoutAdd(Layer):
    """Reference: incubate/nn/layer/fused_dropout_add.py."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F_inc.fused_dropout_add(x, y, p=self.p,
                                       training=self.training,
                                       mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """Reference: incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)
        self.linear_bias = self.create_parameter((embed_dim,),
                                                 is_bias=True)
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon

    def forward(self, x, residual):
        return F_inc.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self._dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedEcMoe(Layer):
    """Reference: incubate/nn/layer/fused_ec_moe.py — expert-choice MoE
    ([E, D, Dff] / [E, Dff, D] expert banks + gate projection)."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        self.gate = self.create_parameter((hidden_size, num_experts))
        self.bmm0_weight = self.create_parameter(
            (num_experts, hidden_size, inter_size))
        self.bmm0_bias = self.create_parameter(
            (num_experts, inter_size), is_bias=True)
        self.bmm1_weight = self.create_parameter(
            (num_experts, inter_size, hidden_size))
        self.bmm1_bias = self.create_parameter(
            (num_experts, hidden_size), is_bias=True)
        self._act = act_type

    def forward(self, x, gate=None):
        gate_logits = gate if gate is not None else x.matmul(self.gate)
        return F_inc.fused_ec_moe(x, gate_logits, self.bmm0_weight,
                                  self.bmm0_bias, self.bmm1_weight,
                                  self.bmm1_bias, act_type=self._act)


class FusedMultiHeadAttention(Layer):
    """Reference: incubate/nn/layer/fused_transformer.py
    FusedMultiHeadAttention (pre/post-LN fused MHA block)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 weight_attr=None, bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        head = embed_dim // num_heads
        self.qkv_weight = self.create_parameter(
            (3, num_heads, head, embed_dim))
        self.qkv_bias = self.create_parameter((3, num_heads, head),
                                              is_bias=True)
        self.linear_weight = self.create_parameter((embed_dim, embed_dim))
        self.linear_bias = self.create_parameter((embed_dim,),
                                                 is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter((embed_dim,),
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)
        self._cfg = dict(pre_layer_norm=normalize_before,
                         dropout_rate=dropout_rate,
                         attn_dropout_rate=attn_dropout_rate,
                         ln_epsilon=epsilon, num_heads=num_heads)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention cache is not wired; use "
                "models/gpt.py's compiled KV decode for serving")
        return F_inc.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self._cfg["pre_layer_norm"],
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask,
            dropout_rate=self._cfg["dropout_rate"] if self.training else 0.0,
            attn_dropout_rate=(self._cfg["attn_dropout_rate"]
                               if self.training else 0.0),
            ln_epsilon=self._cfg["ln_epsilon"], training=self.training,
            num_heads=self._cfg["num_heads"])


class FusedFeedForward(Layer):
    """Reference: incubate/nn/layer/fused_transformer.py
    FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward))
        self.linear1_bias = self.create_parameter((dim_feedforward,),
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model))
        self.linear2_bias = self.create_parameter((d_model,),
                                                  is_bias=True)
        self.ln1_scale = self.create_parameter(
            (d_model,), default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter((d_model,), is_bias=True)
        self.ln2_scale = self.create_parameter(
            (d_model,), default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter((d_model,), is_bias=True)
        self._cfg = dict(dropout_rate=dropout_rate, epsilon=epsilon,
                         activation=activation,
                         act_dropout_rate=(act_dropout_rate
                                           if act_dropout_rate is not None
                                           else dropout_rate),
                         normalize_before=normalize_before)

    def forward(self, x):
        return F_inc.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias,
            linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=(self._cfg["act_dropout_rate"]
                           if self.training else 0.0),
            dropout2_rate=(self._cfg["dropout_rate"]
                           if self.training else 0.0),
            activation=self._cfg["activation"],
            ln1_epsilon=self._cfg["epsilon"],
            ln2_epsilon=self._cfg["epsilon"],
            pre_layer_norm=self._cfg["normalize_before"],
            training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """Reference: incubate/nn/layer/fused_transformer.py
    FusedTransformerEncoderLayer = FusedMultiHeadAttention +
    FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate
                               if attn_dropout_rate is not None
                               else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedTransformerEncoderLayer cache is not wired")
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """Reference: incubate/nn/layer/fused_transformer.py
    FusedMultiTransformer — the whole pre-LN stack as one op (serving
    fast path; rides functional.fused_multi_transformer)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before
                 =True, ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, ring_id=-1,
                 name=None):
        super().__init__()
        assert normalize_before, (
            "FusedMultiTransformer is the pre-LN serving stack "
            "(reference constraint)")
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if qkv_weight_attrs else 1
        head = embed_dim // num_heads

        def mk(shape, attrs, i, is_bias=False, ones=False):
            # per-layer ParamAttr lists (initializers included) are honored
            attr = attrs[i] if attrs is not None else None
            return self.create_parameter(
                shape, attr=attr, is_bias=is_bias,
                default_initializer=I.Constant(1.0) if ones else None)

        L = num_layers
        self.ln_scales = [mk((embed_dim,), ln_scale_attrs, i, ones=True)
                          for i in range(L)]
        self.ln_biases = [mk((embed_dim,), ln_bias_attrs, i, is_bias=True)
                          for i in range(L)]
        self.qkv_weights = [mk((3, num_heads, head, embed_dim),
                               qkv_weight_attrs, i) for i in range(L)]
        self.qkv_biases = [mk((3, num_heads, head), qkv_bias_attrs, i,
                              is_bias=True) for i in range(L)]
        self.linear_weights = [mk((embed_dim, embed_dim),
                                  linear_weight_attrs, i)
                               for i in range(L)]
        self.linear_biases = [mk((embed_dim,), linear_bias_attrs, i,
                                 is_bias=True) for i in range(L)]
        self.ffn_ln_scales = [mk((embed_dim,), ffn_ln_scale_attrs, i,
                                 ones=True) for i in range(L)]
        self.ffn_ln_biases = [mk((embed_dim,), ffn_ln_bias_attrs, i,
                                 is_bias=True) for i in range(L)]
        self.ffn1_weights = [mk((embed_dim, dim_feedforward),
                                ffn1_weight_attrs, i) for i in range(L)]
        self.ffn1_biases = [mk((dim_feedforward,), ffn1_bias_attrs, i,
                               is_bias=True) for i in range(L)]
        self.ffn2_weights = [mk((dim_feedforward, embed_dim),
                                ffn2_weight_attrs, i) for i in range(L)]
        self.ffn2_biases = [mk((embed_dim,), ffn2_bias_attrs, i,
                               is_bias=True) for i in range(L)]
        # register the per-layer parameter lists so parameters() sees them
        for i in range(num_layers):
            for group in ("ln_scales", "ln_biases", "qkv_weights",
                          "qkv_biases", "linear_weights", "linear_biases",
                          "ffn_ln_scales", "ffn_ln_biases", "ffn1_weights",
                          "ffn1_biases", "ffn2_weights", "ffn2_biases"):
                self.add_parameter(f"{group}_{i}",
                                   getattr(self, group)[i])
        self._cfg = dict(epsilon=epsilon, activation=activation,
                         dropout_rate=dropout_rate)

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        if caches is not None or time_step is not None:
            raise NotImplementedError(
                "FusedMultiTransformer incremental decoding (caches/"
                "time_step) is not wired; use models/gpt.py's compiled "
                "fixed-shape KV decode for serving")
        return F_inc.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=True, epsilon=self._cfg["epsilon"],
            attn_mask=attn_mask, activation=self._cfg["activation"],
            dropout_rate=(self._cfg["dropout_rate"] if self.training
                          else 0.0), training=self.training)
