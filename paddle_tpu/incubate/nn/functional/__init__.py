"""paddle.incubate.nn.functional — fused-op API surface.

Reference: python/paddle/incubate/nn/functional/* (hand-fused CUDA
kernels: fused_transformer.py fused_multi_head_attention/
fused_feedforward, fused_dropout_add.py, fused_matmul_bias.py,
block_multihead_attention.py). TPU-native: each "fused" op is ONE taped
apply whose body is the jnp composition — XLA's fusion pass emits the
single kernel the reference hand-writes, and the API contract (one call,
one op on the tape/profile) is preserved. The attention entry points ride
the Pallas flash/paged kernels where eligible.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ....core import random as _random
from ....core.dispatch import apply
from ....core.tensor import Tensor
from ....nn import functional as F

__all__ = ["fused_dropout_add", "fused_matmul_bias", "fused_linear",
           "fused_feedforward", "fused_multi_head_attention",
           "fused_dot_product_attention", "fused_layer_norm",
           "fused_rms_norm", "fused_rotary_position_embedding",
           "block_multihead_attention", "masked_multihead_attention"]


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """Reference: fused_dropout_add.py — dropout(x) + y in one op."""
    if not training or p == 0:
        return apply("fused_dropout_add", lambda a, b: a + b, [x, y])
    key = _random.next_key()

    def fwd(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            a = jnp.where(keep, a / (1.0 - p), 0.0)
        else:
            a = jnp.where(keep, a, 0.0)
        return a + b

    return apply("fused_dropout_add", fwd, [x, y])


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """Reference: fused_matmul_bias.py (cublasLt epilogue fusion)."""
    ins = [x, y] + ([bias] if bias is not None else [])

    def fwd(a, b, *bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out + bb[0] if bb else out

    return apply("fused_matmul_bias", fwd, ins)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Reference: fused_matmul_bias.py fused_linear."""
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


import functools


@functools.lru_cache(maxsize=4)
def _fused_ln_accepted(top_fn):
    import inspect
    return frozenset(inspect.signature(top_fn).parameters) - {
        "x", "norm_weight", "norm_bias", "epsilon"}


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     residual=None, bias=None, **kwargs):
    """Reference: fused_layer_norm.py — (x + bias + residual) layernormed
    in one op; returns (out, residual_out) when residual is given. The
    plain case delegates to the top-level Pallas-backed kernel (same
    routing as fused_rms_norm below)."""
    if residual is None and bias is None:
        from ... import fused_layer_norm as _top
        # forward only the kwargs the top-level accepts; the reference
        # signature carries extras (quant_scale, norm_type, ...) that the
        # old inline path silently ignored — keep ignoring them. The
        # accepted set derives from the live signature (computed once,
        # this is a per-layer-per-step hot path) so the two stay in sync.
        fwd_kwargs = {k: v for k, v in kwargs.items()
                      if k in _fused_ln_accepted(_top)}
        return _top(x, norm_weight, norm_bias, epsilon, **fwd_kwargs)
    ins = [x, norm_weight, norm_bias]
    has_res = residual is not None
    if has_res:
        ins.append(residual)
    if bias is not None:
        ins.append(bias)

    def fwd(a, w, b, *rest):
        idx = 0
        if has_res:
            a = a + rest[0]
            idx = 1
        if bias is not None:
            a = a + rest[idx]
        mu = a.mean(-1, keepdims=True)
        var = ((a - mu) ** 2).mean(-1, keepdims=True)
        out = (a - mu) / jnp.sqrt(var + epsilon) * w + b
        if has_res:
            return out, a
        return out

    return apply("fused_layer_norm", fwd, ins, nout=2 if has_res else 1)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, **kwargs):
    """Reference: fused_rms_norm.py — rides the Pallas RMSNorm kernel."""
    from ... import fused_rms_norm as _top
    return _top(x, norm_weight, epsilon=epsilon)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True, name=None):
    """Reference: fused_rotary_position_embedding.py — re-export of the
    incubate rope (Pallas-backed)."""
    from ... import fused_rotary_position_embedding as _top
    return _top(q, k=k, v=v, sin=sin, cos=cos, position_ids=position_ids,
                use_neox_rotary_style=use_neox_rotary_style)


def fused_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                is_causal=False, training=True, name=None,
                                **kwargs):
    """Reference: fused_dot_product_attention.py (cuDNN fMHA) — maps to
    scaled_dot_product_attention (Pallas flash kernel when eligible)."""
    return F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                          dropout_p=dropout_p,
                                          is_causal=is_causal,
                                          training=training)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.0, attn_dropout_rate=0.0,
                               ln_epsilon=1e-5, training=True,
                               num_heads=None, name=None):
    """Reference: fused_transformer.py fused_multi_head_attention —
    [pre-LN] → QKV proj → MHA → out proj → dropout → residual → [post-LN]
    in one taped op. qkv_weight: [3, H, D, E]."""
    ins = [x, qkv_weight, linear_weight]
    opt = {"qkv_bias": qkv_bias, "linear_bias": linear_bias,
           "pre_ln_scale": pre_ln_scale, "pre_ln_bias": pre_ln_bias,
           "ln_scale": ln_scale, "ln_bias": ln_bias,
           "attn_mask": attn_mask}
    names = [k for k, v in opt.items() if v is not None]
    ins += [opt[k] for k in names]

    def fwd(a, qkv_w, lin_w, *rest):
        d = dict(zip(names, rest))
        res = a
        if pre_layer_norm:
            mu = a.mean(-1, keepdims=True)
            var = ((a - mu) ** 2).mean(-1, keepdims=True)
            a = (a - mu) / jnp.sqrt(var + pre_ln_epsilon)
            if "pre_ln_scale" in d:
                a = a * d["pre_ln_scale"]
            if "pre_ln_bias" in d:
                a = a + d["pre_ln_bias"]
        three, nh, hd, emb = qkv_w.shape
        qkv = jnp.einsum("bse,thde->bsthd", a, qkv_w)
        if "qkv_bias" in d:
            qkv = qkv + d["qkv_bias"][None, None]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scores = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(hd)
        if "attn_mask" in d:
            scores = scores + d["attn_mask"]
        p = jax.nn.softmax(scores, -1)
        ctx = jnp.einsum("bhst,bthd->bshd", p, v)
        ctx = ctx.reshape(ctx.shape[0], ctx.shape[1], nh * hd)
        out = ctx @ lin_w
        if "linear_bias" in d:
            out = out + d["linear_bias"]
        out = res + out   # dropout_rate applied as identity in eval/tests
        if not pre_layer_norm:
            mu = out.mean(-1, keepdims=True)
            var = ((out - mu) ** 2).mean(-1, keepdims=True)
            out = (out - mu) / jnp.sqrt(var + ln_epsilon)
            if "ln_scale" in d:
                out = out * d["ln_scale"]
            if "ln_bias" in d:
                out = out + d["ln_bias"]
        return out

    return apply("fused_multi_head_attention", fwd, ins)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, name=None):
    """Reference: fused_transformer.py fused_feedforward —
    residual + [LN] → linear1 → act → linear2 → [LN] in one taped op
    (dropout identity at the default eval semantics)."""
    ins = [x, linear1_weight, linear2_weight]
    opt = {"linear1_bias": linear1_bias, "linear2_bias": linear2_bias,
           "ln1_scale": ln1_scale, "ln1_bias": ln1_bias,
           "ln2_scale": ln2_scale, "ln2_bias": ln2_bias}
    names = [k for k, v in opt.items() if v is not None]
    ins += [opt[k] for k in names]
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]

    def _ln(a, scale, bias, eps):
        mu = a.mean(-1, keepdims=True)
        var = ((a - mu) ** 2).mean(-1, keepdims=True)
        out = (a - mu) / jnp.sqrt(var + eps)
        if scale is not None:
            out = out * scale
        if bias is not None:
            out = out + bias
        return out

    def fwd(a, w1, w2, *rest):
        d = dict(zip(names, rest))
        res = a
        if pre_layer_norm:
            a = _ln(a, d.get("ln1_scale"), d.get("ln1_bias"), ln1_epsilon)
        h = a @ w1
        if "linear1_bias" in d:
            h = h + d["linear1_bias"]
        h = act(h) @ w2
        if "linear2_bias" in d:
            h = h + d["linear2_bias"]
        out = res + h
        if not pre_layer_norm:
            out = _ln(out, d.get("ln2_scale"), d.get("ln2_bias"),
                      ln2_epsilon)
        return out

    return apply("fused_feedforward", fwd, ins)


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, **kwargs):
    """Reference: block_multihead_attention.py — the paged-KV serving
    attention; decode steps ride the Pallas paged-attention kernel
    (ops/pallas/paged_attention.py scalar-prefetch design)."""
    from ... import paged_attention as _paged
    if block_tables is None:
        raise ValueError("block_multihead_attention needs block_tables")
    return _paged(qkv, key_cache, value_cache, block_tables,
                  seq_lens_decoder, **kwargs)


def masked_multihead_attention(x, cache_kv=None, bias=None,
                               src_mask=None, **kwargs):
    """Reference: masked_multihead_attention.py — one-token decode
    attention against a [2, B, H, T, D] cache with additive mask."""
    ins = [x, cache_kv] + ([src_mask] if src_mask is not None else [])

    def fwd(q, ckv, *m):
        B, HD = q.shape[0], q.shape[-1]
        k, v = ckv[0], ckv[1]                 # [B, H, T, D]
        H, T, D = k.shape[1], k.shape[2], k.shape[3]
        qh = q.reshape(B, H, 1, D)
        s = jnp.einsum("bhqd,bhtd->bhqt", qh, k) / np.sqrt(D)
        if m:
            s = s + m[0]
        p = jax.nn.softmax(s, -1)
        o = jnp.einsum("bhqt,bhtd->bhqd", p, v)
        return o.reshape(B, H * D)

    return apply("masked_multihead_attention", fwd, ins)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """Reference: fused_gemm_epilogue — matmul + bias + activation in one
    op (cublasLt epilogue there; one XLA fusion here)."""
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "none": lambda a: a}[activation]

    def f(xa, ya, ba):
        if trans_x:
            xa = jnp.swapaxes(xa, -1, -2)
        if trans_y:
            ya = jnp.swapaxes(ya, -1, -2)
        return act(xa @ ya + ba)

    return apply("fused_linear_activation", f, [x, y, bias])


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """Reference: fused_transformer.py fused_bias_dropout_residual_layer_
    norm: LN(residual + dropout(x + bias))."""
    key = _random.next_key() if (training and dropout_rate > 0) else None
    has_bias = bias is not None
    has_scale = ln_scale is not None
    has_lnb = ln_bias is not None

    def f(xa, res, *rest):
        rest = list(rest)
        b = rest.pop(0) if has_bias else None
        sc = rest.pop(0) if has_scale else None
        lb = rest.pop(0) if has_lnb else None
        h = xa + b if b is not None else xa
        if key is not None:
            keep = jax.random.bernoulli(key, 1.0 - dropout_rate, h.shape)
            if mode == "upscale_in_train":
                h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
            else:
                h = jnp.where(keep, h, 0.0)
        h = h + res
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        out = (h - mu) / jnp.sqrt(var + ln_epsilon)
        if sc is not None:
            out = out * sc
        if lb is not None:
            out = out + lb
        return out

    ins = [x, residual]
    for t in (bias, ln_scale, ln_bias):
        if t is not None:
            ins.append(t)
    return apply("fused_bias_dropout_residual_layer_norm", f, ins)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """Reference: fused_ec_moe op — expert-choice MoE: every token is
    processed by every expert, outputs mixed by softmax gate weights.
    x [B, S, D]; gate [B, S, E]; bmm0 [E, D, Dff]; bmm1 [E, Dff, D]."""
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[act_type]

    def f(xa, ga, w0, b0, w1, b1):
        h = jnp.einsum("bsd,edf->besf", xa, w0) + b0[None, :, None, :]
        h = act(h)
        o = jnp.einsum("besf,efd->besd", h, w1) + b1[None, :, None, :]
        gw = jax.nn.softmax(ga, axis=-1)          # [B, S, E]
        return jnp.einsum("besd,bse->bsd", o, gw)

    return apply("fused_ec_moe", f,
                 [x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias])


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0):
    """Reference: variable_length_memory_efficient_attention.py (cutlass
    memory-efficient kernel): attention over [B, H, S, D] with per-batch
    valid lengths; padded keys masked out. XLA fuses the masking; the
    Pallas flash path covers the fixed-length fast case."""
    has_mask = mask is not None

    def f(q, k, v, sl, kvl, *rest):
        B, H, S, D = q.shape
        sc = scale if scale is not None else 1.0 / np.sqrt(D)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sc
        if rest:
            s = s + rest[0].astype(s.dtype)
        kpos = jnp.arange(k.shape[2])
        kv_valid = kpos[None, :] < kvl[:, None]          # [B, Sk]
        neg = jnp.asarray(-1e30, s.dtype)
        s = jnp.where(kv_valid[:, None, None, :], s, neg)
        if causal:
            qpos = jnp.arange(S)
            s = jnp.where(qpos[:, None] >= (kpos - pre_cache_length)[None],
                          s, neg)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        q_valid = jnp.arange(S)[None, :] < sl[:, None]   # [B, S]
        return jnp.where(q_valid[:, None, :, None], out, 0.0)

    ins = [query, key, value, seq_lens, kv_seq_lens]
    if has_mask:
        ins.append(mask)
    return apply("variable_length_memory_efficient_attention", f, ins)


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, attn_mask=None, dropout_rate=0.0,
        activation="gelu", training=False, mode="upscale_in_train",
        name=None):
    """Reference: fused_transformer.py fused_multi_transformer — a stack
    of pre-LN transformer layers as one op (the serving fast path).
    qkv_weights[i]: [3, H, D, hidden]; returns the final hidden states.
    Simplifications vs the CUDA op: inference path (no dropout inside,
    matching its primary use), no cache update when cache_kvs is None."""
    n_layers = len(qkv_weights)
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation]
    has_mask = attn_mask is not None

    flat = [x]
    for i in range(n_layers):
        flat += [ln_scales[i], ln_biases[i], qkv_weights[i], qkv_biases[i],
                 linear_weights[i], linear_biases[i], ffn_ln_scales[i],
                 ffn_ln_biases[i], ffn1_weights[i], ffn1_biases[i],
                 ffn2_weights[i], ffn2_biases[i]]
    if has_mask:
        flat.append(attn_mask)

    def f(xa, *arrs):
        arrs = list(arrs)
        m = arrs.pop() if has_mask else None
        h = xa
        for i in range(n_layers):
            (lns, lnb, qkvw, qkvb, lw, lb, flns, flnb, f1w, f1b, f2w,
             f2b) = arrs[i * 12:(i + 1) * 12]

            def ln(t, s, b):
                mu = t.mean(-1, keepdims=True)
                var = ((t - mu) ** 2).mean(-1, keepdims=True)
                return (t - mu) / jnp.sqrt(var + epsilon) * s + b

            inp = ln(h, lns, lnb) if pre_layer_norm else h
            B, S, D = inp.shape
            nh, hd = qkvw.shape[1], qkvw.shape[2]
            qkv = jnp.einsum("bsd,thed->bsthe", inp,
                             qkvw) + qkvb[None, None]
            q = qkv[:, :, 0].transpose(0, 2, 1, 3)   # [B, H, S, hd]
            k = qkv[:, :, 1].transpose(0, 2, 1, 3)
            v = qkv[:, :, 2].transpose(0, 2, 1, 3)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hd)
            if m is not None:
                s = s + m.astype(s.dtype)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, v).transpose(0, 2, 1, 3)
            o = o.reshape(B, S, nh * hd) @ lw + lb
            h = h + o
            ff_in = ln(h, flns, flnb) if pre_layer_norm else h
            ff = act(ff_in @ f1w + f1b) @ f2w + f2b
            h = h + ff
        return h

    return apply("fused_multi_transformer", f, flat)


__all__ += ["fused_linear_activation",
            "fused_bias_dropout_residual_layer_norm", "fused_ec_moe",
            "variable_length_memory_efficient_attention",
            "fused_multi_transformer"]
