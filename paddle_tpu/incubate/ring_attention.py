"""Ring attention — context parallelism over the 'sep' mesh axis.

Reference gap (SURVEY §5 long-context): the reference snapshot has Megatron-SP
and the 'sep' axis but NO ring attention/blockwise CP; PAPERS.md directs the
TPU rebuild to add it. Design: Q/K/V are sharded on the sequence dim across
the ring; each step computes the local block's contribution with
online-softmax accumulation (flash-attention math), then rotates K/V to the
next neighbour with `jax.lax.ppermute` — the collective rides ICI neighbour
links, overlapping compute with transfer. Memory per device is O(S/N), so
context length scales linearly with ring size.

Causal masking is handled per ring step at block granularity: the K/V block
that originated on device j is fully visible to queries on device i when
j < i, fully hidden when j > i, and diagonal (within-block causal) when
j == i.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["ring_attention"]


def _ring_attention_local(q, k, v, *, axis, causal, scale):
    """Per-device body (inside shard_map). q/k/v: [B, S_local, H, D]."""
    n = jax.lax.psum(1, axis)
    my_idx = jax.lax.axis_index(axis)
    b, s_loc, h, d = q.shape
    qf = jnp.swapaxes(q.astype(jnp.float32), 1, 2)  # [B, H, Sl, D]

    def block(kv, src_idx):
        """Attention stats of local Q against one K/V block."""
        kb, vb = kv
        kf = jnp.swapaxes(kb.astype(jnp.float32), 1, 2)
        vf = jnp.swapaxes(vb.astype(jnp.float32), 1, 2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
        if causal:
            qpos = my_idx * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 0)
            kpos = src_idx * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 1)
            s = jnp.where(kpos <= qpos, s, -1e30)
        m = s.max(axis=-1)                                   # [B,H,Sl]
        p = jnp.exp(s - m[..., None])
        l = p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
        return m, l, pv

    def accumulate(carry, i, rotate):
        kb, vb, m_acc, l_acc, o_acc = carry
        src_idx = (my_idx - i) % n  # who this K/V block belongs to
        m_b, l_b, pv_b = block((kb, vb), src_idx)
        m_new = jnp.maximum(m_acc, m_b)
        c_old = jnp.exp(m_acc - m_new)
        c_new = jnp.exp(m_b - m_new)
        l_new = c_old * l_acc + c_new * l_b
        o_new = c_old[..., None] * o_acc + c_new[..., None] * pv_b
        if rotate:
            # rotate K/V around the ring (device r sends to r+1)
            perm = [(j, (j + 1) % n) for j in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
        return (kb, vb, m_new, l_new, o_new)

    m0 = jnp.full((b, h, s_loc), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    carry = (k, v, m0, l0, o0)
    if n > 1:
        carry, _ = jax.lax.scan(
            lambda c, i: (accumulate(c, i, rotate=True), None),
            carry, jnp.arange(n - 1))
    # final block: no trailing rotation (its result would be discarded)
    _, _, m_f, l_f, o_f = accumulate(carry, n - 1, rotate=False)
    out = o_f / jnp.maximum(l_f, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)          # [B, Sl, H, D]


def ring_attention(query, key, value, is_causal=True, scale=None,
                   sep_group=None, name=None):
    """Context-parallel attention on [B, S, H, D] tensors whose seq dim is
    (or will be) sharded across the sequence-parallel axis.

    Drop-in for F.scaled_dot_product_attention when S exceeds one device's
    memory; differentiable (the ppermute ring is traced through jax.vjp).
    """
    from ..distributed.topology import get_hybrid_communicate_group
    if sep_group is not None:
        mesh, axis = sep_group.mesh, sep_group.axis
    else:
        hcg = get_hybrid_communicate_group()
        mesh, axis = hcg.mesh, "sep"
    n = mesh.shape[axis]
    d = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    assert query.shape[1] % n == 0, (
        f"seq len {query.shape[1]} must divide the ring size {n}")

    local = functools.partial(_ring_attention_local, axis=axis,
                              causal=is_causal, scale=scale)
    spec = P(None, axis, None, None)

    def fwd(q, k, v):
        fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        # commit seq-dim sharding so the ring actually distributes the work
        q = jax.lax.with_sharding_constraint(q, NamedSharding(mesh, spec))
        k = jax.lax.with_sharding_constraint(k, NamedSharding(mesh, spec))
        v = jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))
        return fn(q, k, v)

    return apply("ring_attention", fwd, [query, key, value])
