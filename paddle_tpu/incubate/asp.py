"""paddle.incubate.asp — automatic structured (n:m) sparsity.

Reference: python/paddle/incubate/asp/ (prune_model computes n:m masks,
ASPHelper re-applies masks after each optimizer step via decorate()).
TPU note: n:m sparsity is a GPU sparse-tensor-core feature; on TPU the
masks still deliver the regularization/compression semantics (weights stay
masked through training), with dense MXU math underneath.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import nn

__all__ = ["prune_model", "decorate", "reset_excluded_layers",
           "set_excluded_layers", "calculate_density"]

# keyed by id(param): masks hold no ref to the param, so if a pruned
# param is GC'd a recycled id could alias — keep a ref alongside the mask
_masks: dict = {}           # id(param) -> (param, mask array)
_excluded: set = set()      # layer full names excluded from pruning


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x):
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float((arr != 0).sum() / arr.size)


def _nm_mask(w, n=2, m=4):
    """Keep the n largest-magnitude entries in every group of m along the
    last axis (reference: asp/utils.py compute_valid_2d_patterns path,
    collapsed to the magnitude rule)."""
    shape = w.shape
    flat = w.reshape(-1, m) if shape[-1] % m == 0 else None
    if flat is None:
        import warnings
        warnings.warn(
            f"asp: weight last dim {shape[-1]} not divisible by m={m}; "
            "layer left dense (not pruned) — calculate_density will "
            "report 1.0 for it")
        return jnp.ones_like(w)  # indivisible tail: leave dense
    idx = jnp.argsort(-jnp.abs(flat), axis=-1)[:, :n]
    mask = jnp.zeros_like(flat, bool)
    rows = jnp.arange(flat.shape[0])[:, None]
    mask = mask.at[rows, idx].set(True)
    return mask.reshape(shape)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute and apply n:m masks to every prunable weight (2-D+ weights
    of Linear/Conv layers; reference supported_layers)."""
    pruned = 0
    for name, layer in model.named_sublayers():
        if name in _excluded or type(layer).__name__ in _excluded:
            continue
        w = getattr(layer, "weight", None)
        if w is None or w._data.ndim < 2:
            continue
        mask = _nm_mask(w._data, n, m)
        _masks[id(w)] = (w, mask)
        w._data = jnp.where(mask, w._data, 0.0)
        pruned += 1
    return pruned


class ASPOptimizerWrapper:
    """Reference: asp/asp.py OptimizerWithSparsityGuarantee — after every
    step, re-apply the masks so pruned weights stay zero."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def step(self):
        self._inner.step()
        for p in self._inner._parameter_list:
            ent = _masks.get(id(p))
            if ent is not None and ent[0] is p:
                p._data = jnp.where(ent[1], p._data, 0.0)

    def __getattr__(self, name):
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)


def decorate(optimizer):
    return ASPOptimizerWrapper(optimizer)
