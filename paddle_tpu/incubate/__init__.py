"""paddle_tpu.incubate — fused ops + MoE (reference: python/paddle/incubate).

On TPU the "fused" ops are either XLA-fused automatically or backed by the
Pallas kernels in ops/pallas; the incubate names are kept for API parity
(reference: incubate/nn/functional/fused_*.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from .moe import MoELayer, TopKGate  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = ["MoELayer", "TopKGate", "ring_attention", "fused_rms_norm",
           "fused_layer_norm", "fused_rotary_position_embedding",
           "flash_attention", "paged_attention", "LookAhead",
           "ModelAverage", "optimizer", "asp"]


def paged_attention(q, k_cache, v_cache, block_tables, context_lens,
                    scale=None, use_pallas=None, interpret=False):
    """Decode-time attention over a paged KV cache (reference:
    incubate block_multihead_attention / fusion
    block_multi_head_attention_kernel.cu). q [B,H,D]; caches
    [num_pages, page_size, H, D]; block_tables [B, max_pages];
    context_lens [B]. The Pallas kernel streams pages via scalar-prefetched
    index maps on TPU; the jnp gather path runs elsewhere. Differentiable
    through the tape (decode-serving typically doesn't need grads, but the
    gather formulation provides them)."""
    import jax as _jax

    from ..core.dispatch import apply

    if use_pallas is None:
        use_pallas = interpret or _jax.default_backend() == "tpu"

    def f(qa, ka, va, bt, cl):
        if use_pallas:
            # trainable variant: pallas forward, reference-path backward
            # (the scalar-prefetch grid has no JVP rule)
            from ..ops.pallas.paged_attention import                 paged_attention_trainable
            return paged_attention_trainable(qa, ka, va, bt, cl,
                                             scale=scale,
                                             interpret=interpret)
        from ..ops.pallas.paged_attention import paged_attention_reference
        return paged_attention_reference(qa, ka, va, bt, cl, scale=scale)

    return apply("paged_attention", f,
                 [q, k_cache, v_cache, block_tables, context_lens])


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, use_pallas=None, interpret=False):
    """Reference: incubate/nn/functional/fused_rms_norm.py
    (fused_layernorm_kernel.cu rms path). On TPU the Pallas kernel
    (ops/pallas/rms_norm.py) does the whole row-normalize in one VMEM pass;
    elsewhere (or begin_norm_axis != -1) the jnp composition is used —
    interpret=True runs the kernel in interpret mode for CPU parity tests.
    """
    import jax as _jax

    from ..core.dispatch import apply
    from ..core.tensor import Tensor

    last_axis = begin_norm_axis in (-1, (x.ndim - 1 if hasattr(x, "ndim")
                                         else None))
    if use_pallas is None:
        use_pallas = interpret or _jax.default_backend() == "tpu"
    if use_pallas and last_axis:
        from ..ops.pallas.rms_norm import rms_norm as _pallas_rms
        ins = [x, norm_weight] + ([norm_bias] if norm_bias is not None
                                  else [])

        def fwd(*arrs):
            xa, wa = arrs[0], arrs[1]
            ba = arrs[2] if len(arrs) > 2 else None
            return _pallas_rms(xa, wa, ba, eps=epsilon,
                               interpret=interpret)

        return apply("fused_rms_norm", fwd, ins)
    from ..nn.functional import rms_norm
    out = rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1):
    from ..nn.functional import layer_norm
    # normalize over ALL dims from begin_norm_axis onward (reference
    # fused_layer_norm begin_norm_axis semantics)
    ax = begin_norm_axis % x.ndim
    return layer_norm(x, list(x.shape[ax:]), norm_weight, norm_bias, epsilon)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style
                                    =True):
    """RoPE (reference: incubate/nn/functional/
    fused_rotary_position_embedding.py). Layout [B, S, H, D]."""
    import numpy as np

    def rope_one(x, sin_a, cos_a):
        def fwd(a, s, c):
            # neox style: rotate halves
            d = a.shape[-1]
            a1, a2 = a[..., : d // 2], a[..., d // 2:]
            rot = jnp.concatenate([-a2, a1], axis=-1)
            return a * c + rot * s
        return apply("rope", fwd, [x, sin_a, cos_a])

    if sin is None or cos is None:
        b, s, h, d = q.shape
        inv = 1.0 / (10000 ** (np.arange(0, d, 2, dtype=np.float32) / d))
        t = np.arange(s, dtype=np.float32)
        freqs = np.outer(t, inv)                        # [S, D/2]
        emb = np.concatenate([freqs, freqs], axis=-1)   # [S, D]
        from ..core.tensor import Tensor
        sin = Tensor(np.sin(emb)[None, :, None, :])
        cos = Tensor(np.cos(emb)[None, :, None, :])
    outs = [rope_one(x, sin, cos) if x is not None else None
            for x in (q, k, v)]
    return tuple(o for o in outs)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, name=None):
    """Reference: paddle.nn.functional.flash_attention.flash_attention."""
    from ..nn.functional import scaled_dot_product_attention
    if return_softmax:
        raise NotImplementedError(
            "return_softmax=True is not supported: the flash kernel never "
            "materializes the softmax matrix (that is the point)")
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal)
    return out, None

from . import autograd  # noqa: F401,E402
