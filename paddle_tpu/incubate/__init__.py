"""paddle_tpu.incubate — fused ops + MoE (reference: python/paddle/incubate).

On TPU the "fused" ops are either XLA-fused automatically or backed by the
Pallas kernels in ops/pallas; the incubate names are kept for API parity
(reference: incubate/nn/functional/fused_*.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from .moe import MoELayer, TopKGate  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

__all__ = ["MoELayer", "TopKGate", "ring_attention", "fused_rms_norm",
           "fused_layer_norm", "fused_rotary_position_embedding",
           "flash_attention", "paged_attention", "LookAhead",
           "ModelAverage", "optimizer", "asp"]


def paged_attention(q, k_cache, v_cache, block_tables, context_lens,
                    scale=None, use_pallas=None, interpret=False):
    """Decode-time attention over a paged KV cache (reference:
    incubate block_multihead_attention / fusion
    block_multi_head_attention_kernel.cu). q [B,H,D]; caches
    [num_pages, page_size, H, D]; block_tables [B, max_pages];
    context_lens [B]. The Pallas kernel streams pages via scalar-prefetched
    index maps on TPU; the jnp gather path runs elsewhere. Differentiable
    through the tape (decode-serving typically doesn't need grads, but the
    gather formulation provides them)."""
    import jax as _jax

    from ..core.dispatch import apply

    if use_pallas is None:
        from ..ops.pallas import _common as _gate
        use_pallas = interpret or (
            _jax.default_backend() == "tpu" and _gate.pallas_default(
                "paged_attention", _gate.shape_sig(q), allow_nearest=True))

    def f(qa, ka, va, bt, cl):
        if use_pallas:
            # trainable variant: pallas forward, reference-path backward
            # (the scalar-prefetch grid has no JVP rule)
            from ..ops.pallas.paged_attention import                 paged_attention_trainable
            return paged_attention_trainable(qa, ka, va, bt, cl,
                                             scale=scale,
                                             interpret=interpret)
        from ..ops.pallas.paged_attention import paged_attention_reference
        return paged_attention_reference(qa, ka, va, bt, cl, scale=scale)

    return apply("paged_attention", f,
                 [q, k_cache, v_cache, block_tables, context_lens])


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, use_pallas=None, interpret=False):
    """Reference: incubate/nn/functional/fused_rms_norm.py
    (fused_layernorm_kernel.cu rms path). On TPU the Pallas kernel
    (ops/pallas/rms_norm.py) does the whole row-normalize in one VMEM pass;
    elsewhere (or begin_norm_axis != -1) the jnp composition is used —
    interpret=True runs the kernel in interpret mode for CPU parity tests.
    """
    import jax as _jax

    from ..core.dispatch import apply
    from ..core.tensor import Tensor

    last_axis = begin_norm_axis in (-1, (x.ndim - 1 if hasattr(x, "ndim")
                                         else None))
    if use_pallas is None:
        # auto: TPU + the demotion gate (PADDLE_TPU_KERNELS / measured
        # A/B verdict) — BENCH_r05 showed the kernel losing on-chip
        from ..ops.pallas import _common as _gate
        use_pallas = interpret or (
            _jax.default_backend() == "tpu" and _gate.pallas_default(
                "rms_norm", _gate.shape_sig(x), allow_nearest=True))
    if use_pallas and last_axis:
        from ..ops.pallas.rms_norm import rms_norm as _pallas_rms
        ins = [x, norm_weight] + ([norm_bias] if norm_bias is not None
                                  else [])

        def fwd(*arrs):
            xa, wa = arrs[0], arrs[1]
            ba = arrs[2] if len(arrs) > 2 else None
            return _pallas_rms(xa, wa, ba, eps=epsilon,
                               interpret=interpret)

        return apply("fused_rms_norm", fwd, ins)
    from ..nn.functional import rms_norm
    out = rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, use_pallas=None, interpret=False):
    """Reference: incubate/nn/functional/fused_layer_norm.py
    (fused_layernorm_kernel.cu). Last-axis normalization rides the Pallas
    kernel (ops/pallas/layer_norm.py — mean+variance+affine in one VMEM
    pass) on TPU; other begin_norm_axis values use the jnp composition.
    interpret=True runs the kernel in interpret mode for CPU parity."""
    import jax as _jax

    from ..core.dispatch import apply

    last_axis = begin_norm_axis in (-1, x.ndim - 1)
    if use_pallas is None:
        from ..ops.pallas import _common as _gate
        use_pallas = interpret or (
            _jax.default_backend() == "tpu" and _gate.pallas_default(
                "layer_norm", _gate.shape_sig(x), allow_nearest=True))
    if use_pallas and last_axis:
        from ..ops.pallas.layer_norm import layer_norm as _pallas_ln
        has_w = norm_weight is not None
        has_b = norm_bias is not None
        ins = [x] + ([norm_weight] if has_w else []) \
            + ([norm_bias] if has_b else [])

        def fwd(*arrs):
            wa = arrs[1] if has_w else None
            ba = arrs[1 + has_w] if has_b else None
            return _pallas_ln(arrs[0], wa, ba, eps=epsilon,
                              interpret=interpret)

        return apply("fused_layer_norm", fwd, ins)
    from ..nn.functional import layer_norm
    # normalize over ALL dims from begin_norm_axis onward (reference
    # fused_layer_norm begin_norm_axis semantics)
    ax = begin_norm_axis % x.ndim
    return layer_norm(x, list(x.shape[ax:]), norm_weight, norm_bias, epsilon)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style
                                    =True):
    """RoPE (reference: incubate/nn/functional/
    fused_rotary_position_embedding.py). Layout [B, S, H, D]."""
    import numpy as np

    def rope_one(x, sin_a, cos_a):
        def fwd(a, s, c):
            # neox style: rotate halves
            d = a.shape[-1]
            a1, a2 = a[..., : d // 2], a[..., d // 2:]
            rot = jnp.concatenate([-a2, a1], axis=-1)
            return a * c + rot * s
        return apply("rope", fwd, [x, sin_a, cos_a])

    if sin is None or cos is None:
        b, s, h, d = q.shape
        inv = 1.0 / (10000 ** (np.arange(0, d, 2, dtype=np.float32) / d))
        t = np.arange(s, dtype=np.float32)
        freqs = np.outer(t, inv)                        # [S, D/2]
        emb = np.concatenate([freqs, freqs], axis=-1)   # [S, D]
        from ..core.tensor import Tensor
        sin = Tensor(np.sin(emb)[None, :, None, :])
        cos = Tensor(np.cos(emb)[None, :, None, :])
    outs = [rope_one(x, sin, cos) if x is not None else None
            for x in (q, k, v)]
    return tuple(o for o in outs)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, name=None):
    """Reference: paddle.nn.functional.flash_attention.flash_attention."""
    from ..nn.functional import scaled_dot_product_attention
    if return_softmax:
        raise NotImplementedError(
            "return_softmax=True is not supported: the flash kernel never "
            "materializes the softmax matrix (that is the point)")
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal)
    return out, None

from . import autograd  # noqa: F401,E402


# -- graph/segment + fused-softmax long tail (reference:
# python/paddle/incubate/__init__.py __all__) ----------------------------

def softmax_mask_fuse(x, mask, name=None):
    """Reference: incubate.softmax_mask_fuse (fused_softmax_mask op):
    softmax(x + mask) in one kernel — XLA fuses the add into the softmax."""
    import jax

    return apply("softmax_mask_fuse",
                 lambda a, m: jax.nn.softmax(a + m, axis=-1), [x, mask])


def softmax_mask_fuse_upper_triangle(x):
    """Reference: incubate.softmax_mask_fuse_upper_triangle — causal
    (lower-triangle visible) fused softmax for [B, H, S, S] scores."""
    import jax

    def f(a):
        S = a.shape[-1]
        causal = jnp.tril(jnp.ones((S, S), bool))
        return jax.nn.softmax(jnp.where(causal, a, jnp.asarray(
            -1e4, a.dtype)), axis=-1)

    return apply("softmax_mask_fuse_upper_triangle", f, [x])


def identity_loss(x, reduction="none"):
    """Reference: incubate.identity_loss — marks a loss for the IPU
    backend; numerically reduce-or-passthrough."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    if red == "mean":
        return apply("identity_loss", jnp.mean, [x])
    if red == "sum":
        return apply("identity_loss", jnp.sum, [x])
    return x


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Reference: incubate.graph_khop_sampler — multi-hop neighbor
    sampling by chaining per-hop samplers (host op, like the reference
    CPU kernel)."""
    from .. import geometric as G
    import numpy as np

    nodes = input_nodes
    rows_all, cols_all = [], []
    frontier = nodes
    for size in sample_sizes:
        nbr, cnt = G.sample_neighbors(row, colptr, frontier,
                                      sample_size=size)
        nnp = np.asarray(nbr._data if hasattr(nbr, "_data") else nbr)
        cnp = np.asarray(cnt._data if hasattr(cnt, "_data") else cnt)
        src = np.repeat(np.asarray(
            frontier._data if hasattr(frontier, "_data") else frontier),
            cnp)
        rows_all.append(nnp)
        cols_all.append(src)
        from ..core.tensor import Tensor as _T
        frontier = _T(jnp.asarray(np.unique(nnp)))
    import numpy as np2
    all_rows = np2.concatenate(rows_all) if rows_all else np2.empty(0)
    all_cols = np2.concatenate(cols_all) if cols_all else np2.empty(0)
    from ..core.tensor import Tensor as _T
    edge_src = _T(jnp.asarray(all_rows.astype(np2.int64)))
    edge_dst = _T(jnp.asarray(all_cols.astype(np2.int64)))
    sample_index = _T(jnp.asarray(np2.unique(
        np2.concatenate([np2.asarray(
            input_nodes._data if hasattr(input_nodes, "_data")
            else input_nodes), all_rows]).astype(np2.int64))))
    reindex = {int(v): i for i, v in enumerate(
        np2.asarray(sample_index._data))}
    local_src = _T(jnp.asarray(np2.asarray(
        [reindex[int(v)] for v in all_rows], np2.int64)))
    local_dst = _T(jnp.asarray(np2.asarray(
        [reindex[int(v)] for v in all_cols], np2.int64)))
    if return_eids:
        raise NotImplementedError(
            "graph_khop_sampler(return_eids=True): edge-id tracking needs "
            "sorted_eids plumbed through the per-hop sampler; sample "
            "without eids or look features up by (src, dst) pairs")
    return local_src, local_dst, sample_index


# reference aliases onto the geometric message-passing family
def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    from .. import geometric as G
    return G.send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                         out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from .. import geometric as G
    return G.sample_neighbors(row, colptr, input_nodes,
                              sample_size=sample_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from .. import geometric as G
    return G.reindex_graph(x, neighbors, count)


def segment_sum(data, segment_ids, name=None):
    from .. import geometric as G
    return G.segment_sum(data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    from .. import geometric as G
    return G.segment_mean(data, segment_ids)


def segment_max(data, segment_ids, name=None):
    from .. import geometric as G
    return G.segment_max(data, segment_ids)


def segment_min(data, segment_ids, name=None):
    from .. import geometric as G
    return G.segment_min(data, segment_ids)


__all__ += ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
            "identity_loss", "graph_send_recv", "graph_khop_sampler",
            "graph_sample_neighbors", "graph_reindex", "segment_sum",
            "segment_mean", "segment_max", "segment_min"]
