"""paddle.linalg — linear-algebra namespace.

Reference: python/paddle/linalg.py (re-exports tensor/linalg.py ops). The op
implementations live in ops/linalg.py (hand-written) and
ops/generated_linalg.py (codegen spine, ops/ops.yaml).
"""
from .ops.linalg import *  # noqa: F401,F403
from .ops.generated_linalg import *  # noqa: F401,F403
from .ops.generated_linalg import lu, lu_unpack, cond, matrix_exp, \
    householder_product  # noqa: F401
