"""paddle.fft — discrete Fourier transforms.

Reference: python/paddle/fft.py (wraps fft C++ kernels over pocketfft/cuFFN
on CPU, cuFFT on GPU); TPU-native: XLA's FFT HLO via jnp.fft, generated from
ops/ops.yaml. Some TPU runtimes don't implement the FFT HLO — those calls
transparently fall back to the host CPU backend through a tape-preserving
device transfer (jax.device_put is differentiable), mirroring the
reference's CPU-kernel fallback.
"""
from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp

from .core.dispatch import apply
from .core.tensor import Tensor
from .ops import generated_fft as _gen
from .ops.generated_fft import __all__ as _gen_all


class _HostMove:
    """Tape-preserving cross-backend transfer via the python-level tape
    (PyLayer): jax.device_put between the axon TPU client and the CPU
    backend is itself UNIMPLEMENTED, but a host fetch (np.asarray) +
    re-upload always works, and the PyLayer backward moves the cotangent
    the same way."""

    @staticmethod
    def move(t, device):
        from .autograd import PyLayer
        import numpy as np

        class _M(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.src = list(x._data.devices())[0]
                return Tensor(jax.device_put(np.asarray(x._data), device),
                              stop_gradient=x.stop_gradient)

            @staticmethod
            def backward(ctx, g):
                return Tensor(jax.device_put(np.asarray(g._data), ctx.src),
                              stop_gradient=True)

        return _M.apply(t)


def _move(t, device):
    return _HostMove.move(t, device)


_FFT_OK = None


def _device_fft_supported():
    """Decide WITHOUT attempting an fft on the device: under the axon
    remote-compile tunnel a failed (UNIMPLEMENTED) compile poisons the
    client — every subsequent fresh compile then fails too — so probing is
    destructive. Real TPU/GPU/CPU runtimes implement the FFT HLO; the axon
    AOT compile helper is the known exception."""
    global _FFT_OK
    if _FFT_OK is None:
        version = getattr(jax.devices()[0].client, "platform_version", "")
        _FFT_OK = "axon" not in version
    return _FFT_OK


def _on_cpu(t):
    try:
        return all(d.platform == "cpu" for d in t._data.devices())
    except Exception:
        return False


def _maybe_back(o, dev):
    # complex dtypes have no home on this TPU runtime — leave them on the
    # host; downstream ffts consume them there, and real-valued results
    # (irfft/hfft/fftshift of reals) return to the accelerator
    if jnp.issubdtype(o._data.dtype, jnp.complexfloating):
        return o
    return _move(o, dev)


def _cpu_fallback(fn):
    @functools.wraps(fn)
    def wrapper(x, *args, **kwargs):
        if _device_fft_supported():
            return fn(x, *args, **kwargs)
        if isinstance(x, Tensor) and isinstance(x._data, jax.core.Tracer):
            raise RuntimeError(
                "paddle.fft inside jit/to_static is unavailable on this "
                "runtime (the device lacks the FFT HLO and the host "
                "fallback cannot run under tracing); call the fft op "
                "eagerly, outside the staged function")
        cpu = jax.local_devices(backend="cpu")[0]
        xc = x
        if isinstance(x, Tensor) and not _on_cpu(x):
            xc = _move(x, cpu)
        out = fn(xc, *args, **kwargs)
        dev = jax.devices()[0]
        if isinstance(out, Tensor):
            return _maybe_back(out, dev)
        return tuple(_maybe_back(o, dev) for o in out)
    return wrapper


_this = sys.modules[__name__]
for _name in _gen_all:
    setattr(_this, _name, _cpu_fallback(getattr(_gen, _name)))


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d).astype(dtype or "float32"))


__all__ = list(_gen_all) + ["fftfreq", "rfftfreq"]
